"""photon-fleet: replicated serving with entity-affinity routing.

The single-process ``ScoringService`` (serving/service.py) is the
degenerate case ROADMAP item 3 promised to outgrow: one process, one
device cannot serve "millions of users". ``ServingFleet`` instates the
multi-host layout the host store was designed around:

    clients ──▶ fleet front door (this module)
                  │  admission control (503: replica id + fleet depth)
                  ▼
              FleetRouter (router.py): entity → shard → owning replica,
                  bounded retry, hedged second-sends
                  │
        ┌─────────┼─────────┐
        ▼         ▼         ▼
    replica 0  replica 1  replica N-1     ← ReplicaSupervisor
    (full ScoringService subprocesses:      (supervisor.py): probes,
     fixed effects replicated, host          heartbeat deadlines,
     store complete, device LRU hot          death → re-home →
     on OWN shards only)                     bounded restart

Failure half (the robustness core — docs/SERVING.md failure ladder):
replica death fails in-flight forwards fast (connection errors, the
``BatcherDied`` discipline one level up), the dead replica's shards
re-home to survivors within ``rehome_deadline_s`` (table swap + health
confirmation; survivors serve them from their own host stores with the
SAME scores), the supervisor restarts the replica, and its shards come
home. Every step is observable: ``ReplicaDied`` / ``ShardRehomed`` /
``ReplicaRecovered`` events, ``photon_fleet_*`` metrics, a ``degraded``
flag on ``/healthz`` while any shard is away from home, and a
fleet-level ``SLOTracker`` burning error budget on shed/unserved
requests.

Parity contract (the PR 1 discipline): every routed request's score is
bit-identical to the single-process ``ScoringService`` on the same
model — replicas RUN that service, and re-homing only changes which one
answers. ``tests/test_fleet.py`` proves it through SIGKILL chaos.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from photon_ml_tpu.serving.metrics import SLOTracker
from photon_ml_tpu.serving.router import (FleetRouter, ReplicaHTTPError,
                                          ReplicaShed, ReplicaUnavailable,
                                          ShardMap)
from photon_ml_tpu.serving.supervisor import UP, ReplicaSupervisor
from photon_ml_tpu.utils.events import (ReplicaDied, ReplicaRecovered,
                                        ShardRehomed, default_emitter)

logger = logging.getLogger("photon_ml_tpu.serving.fleet")


class FleetMetrics:
    """The fleet scoreboard: ``photon_fleet_*`` exposition +
    fleet-level SLO window. Thread-safe (router pool threads, the
    supervisor monitor, and HTTP handler threads all record)."""

    def __init__(self, num_replicas: int, slo_window_s: float = 60.0,
                 slo_availability: float = 0.999,
                 slo_latency_ms: Optional[float] = None):
        self._lock = threading.Lock()
        self.num_replicas = num_replicas
        self.requests_total = 0
        self.requests_by_replica = {i: 0 for i in range(num_replicas)}
        self.shed_total = 0  # fleet admission + replica-shed translations
        self.error_total = 0  # non-retryable replica HTTP errors
        self.unserved_total = 0  # retry budget exhausted (ReplicaUnavailable)
        self.forward_retries_total = 0
        self.forward_errors_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.rehomes_total = 0
        self.rehome_seconds_last = 0.0
        self.rehome_seconds_max = 0.0
        self.rehome_deadline_misses_total = 0
        self.replica_deaths_total = 0
        self.replica_restarts_total = 0
        self.slo = SLOTracker(window_s=slo_window_s,
                              availability_objective=slo_availability,
                              latency_objective_ms=slo_latency_ms)

    # Router callbacks (FleetRouter.metrics protocol).
    def record_retry(self, n: int = 1) -> None:
        with self._lock:
            self.forward_retries_total += n

    def record_forward_error(self, n: int = 1) -> None:
        with self._lock:
            self.forward_errors_total += n

    def record_hedge(self) -> None:
        with self._lock:
            self.hedges_total += 1

    def record_hedge_win(self) -> None:
        with self._lock:
            self.hedge_wins_total += 1

    # Fleet-side records.
    def record_routed(self, replica_counts: dict[int, int]) -> None:
        with self._lock:
            for rid, n in replica_counts.items():
                self.requests_by_replica[rid] = \
                    self.requests_by_replica.get(rid, 0) + n
                self.requests_total += n

    def record_ok(self, latency_s: float, n: int = 1) -> None:
        for _ in range(n):
            self.slo.record_ok(latency_s)

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self.shed_total += n
        self.slo.record_bad("shed", n)

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.error_total += n
        self.slo.record_bad("error", n)

    def record_unserved(self, n: int = 1) -> None:
        with self._lock:
            self.unserved_total += n
        self.slo.record_bad("error", n)

    def record_death(self) -> None:
        with self._lock:
            self.replica_deaths_total += 1

    def record_restart(self) -> None:
        with self._lock:
            self.replica_restarts_total += 1

    def record_rehome(self, seconds: float, deadline_s: float) -> None:
        with self._lock:
            self.rehomes_total += 1
            self.rehome_seconds_last = seconds
            self.rehome_seconds_max = max(self.rehome_seconds_max,
                                          seconds)
            if seconds > deadline_s:
                self.rehome_deadline_misses_total += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "requests_by_replica": dict(self.requests_by_replica),
                "shed_total": self.shed_total,
                "error_total": self.error_total,
                "unserved_total": self.unserved_total,
                "forward_retries_total": self.forward_retries_total,
                "forward_errors_total": self.forward_errors_total,
                "hedges_total": self.hedges_total,
                "hedge_wins_total": self.hedge_wins_total,
                "rehomes_total": self.rehomes_total,
                "rehome_seconds_last": self.rehome_seconds_last,
                "rehome_seconds_max": self.rehome_seconds_max,
                "rehome_deadline_misses_total":
                    self.rehome_deadline_misses_total,
                "replica_deaths_total": self.replica_deaths_total,
                "replica_restarts_total": self.replica_restarts_total,
            }

    def render_text(self, states: dict[int, str],
                    degraded: bool) -> str:
        """Prometheus-style ``photon_fleet_*`` lines (the metric
        catalog rows in docs/OBSERVABILITY.md)."""
        s = self.snapshot()
        lines = [
            f"photon_fleet_replicas {self.num_replicas}",
            f"photon_fleet_degraded {1 if degraded else 0}",
            f"photon_fleet_requests_total {s['requests_total']}",
            f"photon_fleet_shed_total {s['shed_total']}",
            f"photon_fleet_errors_total {s['error_total']}",
            f"photon_fleet_unserved_total {s['unserved_total']}",
            f"photon_fleet_forward_retries_total "
            f"{s['forward_retries_total']}",
            f"photon_fleet_forward_errors_total "
            f"{s['forward_errors_total']}",
            f"photon_fleet_hedges_total {s['hedges_total']}",
            f"photon_fleet_hedge_wins_total {s['hedge_wins_total']}",
            f"photon_fleet_rehomes_total {s['rehomes_total']}",
            f"photon_fleet_rehome_seconds{{window=\"last\"}} "
            f"{s['rehome_seconds_last']:.6f}",
            f"photon_fleet_rehome_seconds{{window=\"max\"}} "
            f"{s['rehome_seconds_max']:.6f}",
            f"photon_fleet_rehome_deadline_misses_total "
            f"{s['rehome_deadline_misses_total']}",
            f"photon_fleet_replica_deaths_total "
            f"{s['replica_deaths_total']}",
            f"photon_fleet_replica_restarts_total "
            f"{s['replica_restarts_total']}",
        ]
        for rid in sorted(states):
            lines.append(
                f"photon_fleet_replica_up{{replica=\"{rid}\"}} "
                f"{1 if states[rid] == UP else 0}")
            lines.append(
                f"photon_fleet_requests_routed_total"
                f"{{replica=\"{rid}\"}} "
                f"{s['requests_by_replica'].get(rid, 0)}")
        slo = self.slo.snapshot()
        lines.append(f"photon_fleet_slo_requests_in_window "
                     f"{slo['requests_in_window']}")
        lines.append(f"photon_fleet_slo_bad_in_window "
                     f"{slo['bad_in_window']}")
        lines.append(f"photon_fleet_slo_availability "
                     f"{slo['availability']:.6f}")
        lines.append(f"photon_fleet_slo_budget_burn_rate "
                     f"{slo['budget_burn_rate']:.6f}")
        for q in ("p50", "p95", "p99"):
            lines.append(f"photon_fleet_slo_latency_ms"
                         f"{{quantile=\"{q}\"}} {slo[q + '_ms']:.4f}")
        return "\n".join(lines) + "\n"


class ServingFleet:
    """N supervised scoring replicas behind one entity-affinity router.

    ``replica_args`` is the ``photon_ml_tpu.cli.serve`` argv tail every
    replica shares (model flags, batching knobs); the fleet appends the
    per-replica plumbing (``--port 0 --ready-file … --replica-id …`` and
    the fault plan, when drilling). Replicas inherit this process's
    environment, so ``JAX_PLATFORMS=cpu`` tests stay on CPU.
    """

    def __init__(
        self,
        replica_args: Sequence[str],
        num_replicas: int,
        workdir: str,
        num_shards: Optional[int] = None,
        route_re_type: Optional[str] = None,
        request_timeout_s: float = 30.0,
        retries: int = 3,
        retry_backoff_s: float = 0.1,
        hedge_after_s: Optional[float] = None,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 1.0,
        heartbeat_deadline_s: float = 2.0,
        rehome_deadline_s: float = 5.0,
        start_timeout_s: float = 120.0,
        max_restarts: int = 3,
        max_inflight: Optional[int] = None,
        fault_plan_file: Optional[str] = None,
        slo_window_s: float = 60.0,
        slo_availability: float = 0.999,
        slo_latency_ms: Optional[float] = None,
        emitter=default_emitter,
    ):
        self.replica_args = list(replica_args)
        self.num_replicas = int(num_replicas)
        self.num_shards = int(num_shards if num_shards is not None
                              else max(8, 2 * self.num_replicas))
        self.workdir = workdir
        self.rehome_deadline_s = float(rehome_deadline_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.fault_plan_file = fault_plan_file
        self.emitter = emitter
        # Fleet admission control: beyond this many in-flight /score
        # bodies the front door sheds (the replicas' own queues are the
        # deeper backstop; this bound keeps the router pool sane).
        self.max_inflight = (int(max_inflight) if max_inflight is not None
                             else 16 * self.num_replicas)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.metrics = FleetMetrics(self.num_replicas,
                                    slo_window_s=slo_window_s,
                                    slo_availability=slo_availability,
                                    slo_latency_ms=slo_latency_ms)
        self.shard_map = ShardMap(self.num_shards, self.num_replicas)
        self.supervisor = ReplicaSupervisor(
            self._replica_argv, self.num_replicas, workdir,
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s,
            heartbeat_deadline_s=heartbeat_deadline_s,
            start_timeout_s=start_timeout_s,
            max_restarts=max_restarts,
            on_death=self._on_death,
            on_recovered=self._on_recovered)
        self.router = FleetRouter(
            self.shard_map, self.supervisor.endpoint,
            route_re_type=route_re_type,
            request_timeout_s=request_timeout_s,
            retries=retries, retry_backoff_s=retry_backoff_s,
            hedge_after_s=hedge_after_s, metrics=self.metrics)
        self._degraded = False
        self._rehoming = False
        self._closed = False

    # -- replica plumbing ----------------------------------------------------

    def _replica_argv(self, replica_id: int, ready_file: str) -> list[str]:
        argv = [sys.executable, "-m", "photon_ml_tpu.cli.serve",
                *self.replica_args,
                "--host", "127.0.0.1", "--port", "0",
                "--ready-file", ready_file,
                "--replica-id", str(replica_id)]
        if self.fault_plan_file:
            argv += ["--fault-plan", self.fault_plan_file]
        return argv

    # -- failure half --------------------------------------------------------

    def _on_death(self, replica_id: int) -> None:
        """Supervisor monitor-thread callback: the rehome window starts
        HERE (detection) and closes when every moved shard's new owner
        confirmed healthy."""
        t0 = time.monotonic()
        self.metrics.record_death()
        # pml: allow[PML015] single-writer publish: only the monitor thread flips these bools; /healthz readers tolerate staleness by design
        self._degraded = True
        self._rehoming = True  # pml: allow[PML015] same single-writer monitor-thread publish as above
        self.emitter.emit(ReplicaDied(replica_id=replica_id,
                                      reason="declared dead by probe"))
        try:
            moved = self.shard_map.mark_down(replica_id)
        except ReplicaUnavailable:
            logger.error("replica %d died and no survivor remains — "
                         "the fleet is down until a restart succeeds",
                         replica_id)
            self._rehoming = False  # pml: allow[PML015] single-writer monitor-thread publish; readers poll
            return
        # Confirm each new owner actually serves before declaring the
        # re-home done — a table swap to another corpse is not recovery.
        from photon_ml_tpu.serving.supervisor import _probe_healthz
        for rid in sorted(set(moved.values())):
            host, port = self.supervisor.endpoint(rid)
            try:
                _probe_healthz(f"http://{host}:{port}",
                               self.probe_timeout_s)
            except (OSError, ValueError) as e:
                logger.warning("re-home target %d not yet healthy "
                               "(%s) — the monitor will handle it", rid, e)
        seconds = time.monotonic() - t0
        self._rehoming = False  # pml: allow[PML015] single-writer monitor-thread publish; readers poll
        self.metrics.record_rehome(seconds, self.rehome_deadline_s)
        self.emitter.emit(ShardRehomed(
            replica_id=replica_id, shards=tuple(sorted(moved)),
            new_owners=tuple(moved[s] for s in sorted(moved)),
            seconds=seconds))
        level = (logger.error if seconds > self.rehome_deadline_s
                 else logger.info)
        level("re-homed %d shard(s) of dead replica %d in %.3fs "
              "(deadline %.3fs)", len(moved), replica_id, seconds,
              self.rehome_deadline_s)

    def _on_recovered(self, replica_id: int) -> None:
        back = self.shard_map.restore(replica_id)
        self.metrics.record_restart()
        self.emitter.emit(ReplicaRecovered(
            replica_id=replica_id, shards_restored=tuple(back)))
        states = self.supervisor.states()
        if all(st == UP for st in states.values()):
            self._degraded = False  # pml: allow[PML015] single-writer monitor-thread publish; healthz re-derives from supervisor states anyway
        logger.info("replica %d recovered; %d shard(s) back home; "
                    "fleet %s", replica_id, len(back),
                    "healthy" if not self._degraded else "still degraded")

    # -- serving -------------------------------------------------------------

    def start(self) -> None:
        os.makedirs(self.workdir, exist_ok=True)
        self.supervisor.start()

    def score(self, request_objs: Sequence[dict],
              want_trace: bool = False) -> dict:
        """Route one /score body through the fleet; returns the merged
        response payload. Raises the router's defined errors — the HTTP
        front end maps them to status codes; programmatic callers get
        the same exception taxonomy."""
        counts: dict[int, int] = {}
        for obj in request_objs:
            rid = self.router.replica_for(obj)
            counts[rid] = counts.get(rid, 0) + 1
        self.metrics.record_routed(counts)
        t0 = time.monotonic()
        out = self.router.score(request_objs, want_trace=want_trace)
        dt = time.monotonic() - t0
        self.metrics.record_ok(dt, n=len(request_objs))
        return out

    def admission_acquire(self) -> bool:
        with self._inflight_lock:
            if self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            return True

    def admission_release(self) -> None:
        with self._inflight_lock:
            self._inflight = max(0, self._inflight - 1)

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def healthz(self) -> dict:
        states = self.supervisor.states()
        degraded = self._degraded or any(st != UP
                                         for st in states.values())
        return {
            "status": "degraded" if degraded else "ok",
            "degraded": degraded,
            "rehoming": self._rehoming,
            "fleet_depth": self.num_replicas,
            "replicas": {str(k): v for k, v in states.items()},
            "num_shards": self.num_shards,
            "shards_away_from_home": sum(
                1 for s in range(self.num_shards)
                if self.shard_map.owner(s) != self.shard_map.home(s)),
        }

    def metrics_text(self) -> str:
        return self.metrics.render_text(self.supervisor.states(),
                                        self.healthz()["degraded"])

    def slo_snapshot(self) -> dict:
        out = self.metrics.slo.snapshot()
        out["lifetime"] = self.metrics.snapshot()
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.router.close()
        self.supervisor.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- fleet HTTP front door ---------------------------------------------------

class _FleetHandler(BaseHTTPRequestHandler):
    """POST /score, GET /metrics, GET /slo, GET /healthz — the same
    surface as one replica, so clients cannot tell the fleet from a
    single ``photon-game-serve`` (except via the richer /healthz)."""

    fleet: ServingFleet = None  # bound by make_fleet_http_server

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/metrics":
            body = self.fleet.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/slo":
            self._json(200, self.fleet.slo_snapshot())
        elif self.path == "/healthz":
            hz = self.fleet.healthz()
            # Degraded is still SERVING (shards re-homed) — 200 with the
            # flag, not a 5xx that would page as an outage.
            self._json(200, hz)
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        fleet = self.fleet
        if self.path != "/score":
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
            reqs = payload.get("requests", [])
            if not isinstance(reqs, list) or not reqs:
                raise ValueError("no requests")
            want_trace = bool(payload.get("trace", False))
        except (ValueError, TypeError, AttributeError, KeyError) as exc:
            self._json(400, {"error": f"malformed request: {exc}"})
            return
        if not fleet.admission_acquire():
            # Fleet-level admission: the 503 names the FLEET (no single
            # replica shed) and carries the depth context the ISSUE's
            # degradation contract requires.
            fleet.metrics.record_shed(len(reqs))
            self._json(503, {
                "error": "fleet admission control: too many in-flight "
                         "score bodies",
                "replica_id": None,
                "fleet_depth": fleet.num_replicas,
                "inflight": fleet.inflight,
                "max_inflight": fleet.max_inflight,
            })
            return
        try:
            out = fleet.score(reqs, want_trace=want_trace)
        except ReplicaShed as exc:
            fleet.metrics.record_shed(len(reqs))
            self._json(503, {
                "error": str(exc),
                "replica_id": exc.replica_id,
                "fleet_depth": fleet.num_replicas,
                "queue_depth": exc.queue_depth,
                "degraded": fleet.healthz()["degraded"],
            })
            return
        except ReplicaUnavailable as exc:
            fleet.metrics.record_unserved(len(reqs))
            self._json(503, {
                "error": str(exc),
                "replica_id": exc.replica_id,
                "fleet_depth": fleet.num_replicas,
                "degraded": True,
            })
            return
        except ReplicaHTTPError as exc:
            fleet.metrics.record_error(len(reqs))
            self._json(exc.status if exc.status >= 400 else 500, {
                "error": str(exc),
                "replica_id": exc.replica_id,
                "fleet_depth": fleet.num_replicas,
            })
            return
        finally:
            fleet.admission_release()
        body = {"scores": out["scores"],
                "uids": [r.get("uid") for r in reqs]}
        if want_trace and out.get("attribution") is not None:
            body["attribution"] = out["attribution"]
        self._json(200, body)

    def log_message(self, fmt, *args):  # access logs off stderr
        logger.debug("fleet http: " + fmt, *args)


def make_fleet_http_server(fleet: ServingFleet, host: str = "127.0.0.1",
                           port: int = 8080) -> ThreadingHTTPServer:
    """Bind the fleet front door (call ``serve_forever`` to serve);
    ``port=0`` picks a free port — it is ``server.server_address[1]``."""
    handler = type("BoundFleetHandler", (_FleetHandler,),
                   {"fleet": fleet})
    return ThreadingHTTPServer((host, port), handler)
