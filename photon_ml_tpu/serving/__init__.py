"""Online inference subsystem: resident model store, micro-batched
low-latency scoring, serving metrics (docs/SERVING.md).

The offline path (cli/game_score.py) loads a model per job; this package
keeps one loaded GameModel resident — fixed effects on device, random
effects hash-sharded on host with an LRU device cache for hot entities —
and streams micro-batched requests through a shape-bucketed jitted scorer.
"""

from photon_ml_tpu.serving.batcher import (BatcherDied, BatcherQueueFull,
                                           DeadlineExceeded, MicroBatcher,
                                           bucket_batch)
from photon_ml_tpu.serving.elastic import (ElasticConfig,
                                           ElasticController,
                                           parse_elastic_config)
from photon_ml_tpu.serving.fleet import (FleetMetrics, ServingFleet,
                                         make_fleet_http_server)
from photon_ml_tpu.serving.metrics import (STAGES, ShardHeat, SLOTracker,
                                           ServingMetrics)
from photon_ml_tpu.serving.model_store import (HashShardedStore,
                                               ResidentModelStore)
from photon_ml_tpu.serving.publish import (BadDelta, CanaryRejected,
                                           DeltaCorrupt, DeltaStore,
                                           ModelDelta, PublishError,
                                           read_delta, validate_delta)
from photon_ml_tpu.serving.router import (FleetRouter, ReplicaHTTPError,
                                          ReplicaShed, ReplicaUnavailable,
                                          ShardMap, route_key)
from photon_ml_tpu.serving.service import (ScoringRequest, ScoringService,
                                           make_http_server,
                                           requests_from_dataset)

from photon_ml_tpu.serving.supervisor import (ReplicaStartupError,
                                              ReplicaSupervisor)

__all__ = [
    "BatcherDied",
    "BatcherQueueFull",
    "DeadlineExceeded",
    "ElasticConfig",
    "ElasticController",
    "MicroBatcher",
    "ShardHeat",
    "bucket_batch",
    "parse_elastic_config",
    "FleetMetrics",
    "FleetRouter",
    "ReplicaHTTPError",
    "ReplicaShed",
    "ReplicaStartupError",
    "ReplicaSupervisor",
    "ReplicaUnavailable",
    "ServingFleet",
    "ShardMap",
    "make_fleet_http_server",
    "route_key",
    "STAGES",
    "SLOTracker",
    "ServingMetrics",
    "HashShardedStore",
    "ResidentModelStore",
    "BadDelta",
    "CanaryRejected",
    "DeltaCorrupt",
    "DeltaStore",
    "ModelDelta",
    "PublishError",
    "read_delta",
    "validate_delta",
    "ScoringRequest",
    "ScoringService",
    "make_http_server",
    "requests_from_dataset",
]
