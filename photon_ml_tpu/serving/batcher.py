"""Micro-batcher: queue scoring requests, flush on size or deadline.

The latency/throughput knob of the serving layer (the Snap ML-style
streaming tradeoff): a flush happens when ``max_batch`` requests are queued
(throughput bound) or when the OLDEST queued request has waited
``max_wait_ms`` (latency bound) — so an idle service scores a lone request
after at most one wait window, and a busy one always ships full batches.

Batch SHAPES are the flush function's concern (the service pads each flush
to a bucketed size so the jitted scorer never sees a new shape in steady
state); the batcher's concern is time: one worker thread, one condition
variable, futures for the callers. ``submit`` is thread-safe and returns a
``concurrent.futures.Future`` resolving to that request's score.

Failure contract (docs/ROBUSTNESS.md) — a future returned by ``submit``
ALWAYS resolves; nothing a flush does can strand a caller:

- a flush that raises fails exactly its batch's futures and the loop
  keeps serving;
- a flush that returns the wrong number of scores fails the batch with a
  defined error instead of leaving the unzipped tail pending forever;
- the worker thread is SUPERVISED: if it dies anyway (a BaseException —
  the injectable ``scoring-thread death`` fault class), every pending
  future fails fast with ``BatcherDied`` and a fresh worker is started
  (``restarts`` counts them; ``on_worker_death`` notifies the owner);
- each request carries a deadline (``default_deadline_s`` /
  per-``submit`` override): an entry that expires in the queue fails
  with ``DeadlineExceeded`` rather than waiting unboundedly;
- the queue is bounded (``max_queue``): when it is full, ``submit``
  raises ``BatcherQueueFull`` immediately — admission control (load
  shedding) instead of unbounded buffering.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

from photon_ml_tpu import obs
from typing import Callable, Optional, Sequence

logger = logging.getLogger("photon_ml_tpu.serving")

# Process-wide request ids: every queued request gets one at submit so a
# request is addressable across the thread boundary — in its span, its
# attribution payload, and the logs (docs/SERVING.md request lifecycle).
_REQUEST_IDS = itertools.count(1)


class BatcherQueueFull(RuntimeError):
    """Admission control: the request queue is at ``max_queue``; the
    caller should shed load (HTTP: 503) rather than buffer unboundedly.
    Carries the observed ``depth`` (and ``max_queue``) so the shed
    response can report how deep the queue actually was."""

    def __init__(self, message: str, depth: Optional[int] = None,
                 max_queue: Optional[int] = None):
        super().__init__(message)
        self.depth = depth
        self.max_queue = max_queue


class BatcherDied(RuntimeError):
    """The worker thread died while this request was pending; the
    request was NOT scored. The batcher restarts its worker — retrying
    the request is safe."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before it was scored."""


@dataclass(eq=False)  # identity semantics: requests may hold numpy arrays
class _Entry:
    request: object
    future: Future = field(default_factory=Future)
    # Monotonic, not wall: the flush deadline and the request-latency
    # metric are DURATIONS — an NTP step against time.time() here either
    # starved flushes or fired them instantly (PML004).
    enqueued_at: float = field(default_factory=time.monotonic)
    deadline: Optional[float] = None  # monotonic; None = no deadline
    request_id: int = 0  # assigned at submit (_REQUEST_IDS)
    # Wall anchor of the enqueue instant, captured only while tracing is
    # on: it places the request span on the cross-thread trace axis
    # (durations still come off ``enqueued_at``'s monotonic clock).
    t0_epoch_ns: Optional[int] = None
    # Stage attribution, filled by the flush function BEFORE the future
    # resolves (serving/service.py) — the happens-before edge that lets
    # whoever holds the future read it race-free after ``result()``.
    attribution: Optional[dict] = None


def bucket_batch(n: int, max_batch: int) -> int:
    """Padded batch size for ``n`` requests: next power of two, capped at
    ``max_batch`` — a log-sized set of shapes, so the jitted scorer
    compiles O(log max_batch) programs total and then never again."""
    if n >= max_batch:
        return max_batch
    return 1 << max(0, (int(n) - 1)).bit_length()


class MicroBatcher:
    """Supervised background flusher over a bounded-delay request queue.

    ``flush_fn(entries)`` scores ``entries`` (a list of _Entry; at most
    ``max_batch``) and returns one float per entry, in order. It runs on
    the worker thread; exceptions propagate to every future in the flush.

    ``max_queue`` bounds queued-but-unflushed entries (None = unbounded,
    the pre-hardening behavior). ``default_deadline_s`` bounds how long
    any entry may wait end-to-end (None = forever). ``on_worker_death``
    is called (exception) after a worker-thread death, once per restart —
    the service counts recoveries through it.
    """

    def __init__(
        self,
        flush_fn: Callable[[Sequence[_Entry]], Sequence[float]],
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        max_queue: Optional[int] = None,
        default_deadline_s: Optional[float] = None,
        on_worker_death: Optional[Callable[[BaseException], None]] = None,
        on_deadline: Optional[Callable[[int], None]] = None,
        depth_gauge=None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._flush_fn = flush_fn
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait_ms) / 1e3
        self.max_queue = None if max_queue is None else int(max_queue)
        self.default_deadline = (None if default_deadline_s is None
                                 else float(default_deadline_s))
        self._on_worker_death = on_worker_death
        self._on_deadline = on_deadline
        # An obs-style gauge (set() + peak tracking) observed on every
        # queue transition — the queue depth was previously invisible
        # between "empty" and "BatcherQueueFull" (ISSUE 8 satellite).
        self._depth_gauge = depth_gauge
        self._queue: list[_Entry] = []
        self._inflight: list[_Entry] = []  # batch being flushed right now
        self._cond = threading.Condition()
        self._running = True
        self.restarts = 0  # worker deaths recovered from
        self.expired = 0  # entries failed on their deadline
        self._worker = self._spawn_worker()

    def _spawn_worker(self) -> threading.Thread:
        t = threading.Thread(target=self._loop,
                             name="photon-serving-batcher",
                             daemon=True)
        t.start()
        return t

    def submit(self, request, deadline_s: Optional[float] = None) -> Future:
        """Queue one request. Raises ``BatcherQueueFull`` when admission
        control rejects it; otherwise the returned future ALWAYS
        resolves — with the score, the flush error, ``DeadlineExceeded``,
        or ``BatcherDied``."""
        entry = _Entry(request)
        entry.request_id = next(_REQUEST_IDS)
        tr = obs.tracer()
        if tr is not None:  # wall anchor for the request span (off: one
            entry.t0_epoch_ns = time.time_ns()  # None check)
        ttl = self.default_deadline if deadline_s is None else deadline_s
        if ttl is not None:
            entry.deadline = entry.enqueued_at + float(ttl)
        with self._cond:
            if not self._running:
                raise RuntimeError("batcher is closed")
            depth = len(self._queue)
            if self.max_queue is not None and depth >= self.max_queue:
                raise BatcherQueueFull(
                    f"scoring queue is full ({depth} pending, "
                    f"max {self.max_queue}); shedding load",
                    depth=depth, max_queue=self.max_queue)
            self._queue.append(entry)
            if self._depth_gauge is not None:
                self._depth_gauge.set(depth + 1)
            self._cond.notify()
        return entry.future

    # -- worker ------------------------------------------------------------

    def _expire_locked(self, now: float) -> list[_Entry]:
        """Remove queued entries whose deadline passed (caller holds the
        lock); their futures are failed OUTSIDE the lock by the caller."""
        if not any(e.deadline is not None for e in self._queue):
            return []
        expired = [e for e in self._queue
                   if e.deadline is not None and now >= e.deadline]
        if expired:
            dead = {id(e) for e in expired}
            # pml: allow[PML005] every caller holds self._cond (the
            # _locked suffix is the contract; asserted in tests)
            self._queue = [e for e in self._queue if id(e) not in dead]
            if self._depth_gauge is not None:
                self._depth_gauge.set(len(self._queue))
        return expired

    def _fail_entries(self, entries: Sequence[_Entry],
                      exc: BaseException) -> None:
        for e in entries:
            if not e.future.done():
                e.future.set_exception(exc)

    def _loop(self) -> None:
        # Supervision wrapper: _serve only exits cleanly on close().
        # ANYTHING escaping it — including BaseExceptions that sail past
        # the per-flush handler — fails every pending future fast and
        # restarts the worker, so no submitter ever hangs on a dead
        # thread.
        try:
            self._serve()
        except BaseException as exc:
            self._recover(exc)

    def _recover(self, exc: BaseException) -> None:
        logger.exception("batcher worker died (%s) — failing pending "
                         "futures and restarting", type(exc).__name__)
        with self._cond:
            # The batch that was mid-flush when the thread died is no
            # longer queued — it must fail fast too, or its callers hang.
            pending = self._inflight + self._queue
            self._inflight = []
            self._queue = []
            if self._depth_gauge is not None:
                self._depth_gauge.set(0)
            restart = self._running
            if restart:
                self.restarts += 1
                self._worker = self._spawn_worker()
        self._fail_entries(pending, BatcherDied(
            f"batcher worker died: {type(exc).__name__}: {exc}"))
        if restart and self._on_worker_death is not None:
            try:
                self._on_worker_death(exc)
            except Exception:
                logger.exception("on_worker_death callback failed")

    def _serve(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._queue:
                    self._cond.wait()
                if not self._running and not self._queue:
                    return
                # Wait out the remainder of the oldest entry's window
                # unless the batch is already full (or we're draining);
                # entries whose own deadline expires first are failed,
                # not flushed.
                expired = self._expire_locked(time.monotonic())
                deadline = (self._queue[0].enqueued_at + self.max_wait
                            if self._queue else 0.0)
                while (self._running and self._queue
                       and len(self._queue) < self.max_batch
                       and (left := deadline - time.monotonic()) > 0):
                    entry_deadlines = [e.deadline for e in self._queue
                                       if e.deadline is not None]
                    if entry_deadlines:
                        left = min(left, max(
                            0.0, min(entry_deadlines) - time.monotonic()))
                    self._cond.wait(timeout=max(left, 1e-4))
                    expired.extend(self._expire_locked(time.monotonic()))
                    deadline = (self._queue[0].enqueued_at + self.max_wait
                                if self._queue else 0.0)
                batch = self._queue[: self.max_batch]
                del self._queue[: len(batch)]
                self._inflight = batch
                if self._depth_gauge is not None:
                    self._depth_gauge.set(len(self._queue))
            if expired:
                self.expired += len(expired)
                self._fail_entries(expired, DeadlineExceeded(
                    "request expired in the scoring queue"))
                if self._on_deadline is not None:
                    try:
                        self._on_deadline(len(expired))
                    except Exception:
                        logger.exception("on_deadline callback failed")
            if not batch:
                continue
            try:
                # One span per device flush (docs/OBSERVABILITY.md) —
                # off, this is one None check per batch.
                with obs.span("serving.flush", cat="serving",
                              rows=len(batch)):
                    scores = self._flush_fn(batch)
                if len(scores) != len(batch):
                    # A silent zip() over a short result left the tail
                    # pending FOREVER pre-hardening; fail loudly instead.
                    raise RuntimeError(
                        f"flush returned {len(scores)} scores for "
                        f"{len(batch)} requests")
                for entry, score in zip(batch, scores):
                    if not entry.future.done():
                        entry.future.set_result(score)
            except Exception as exc:  # propagate to callers, keep serving
                self._fail_entries(batch, exc)
            # NOT a finally: a BaseException must leave _inflight set so
            # the supervisor (_recover) can fail this batch fast.
            with self._cond:
                self._inflight = []

    def close(self) -> None:
        """Drain the queue, then stop the worker (idempotent)."""
        with self._cond:
            self._running = False
            worker = self._worker
            self._cond.notify_all()
        if worker.is_alive():
            worker.join()
