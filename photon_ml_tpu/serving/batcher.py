"""Micro-batcher: queue scoring requests, flush on size or deadline.

The latency/throughput knob of the serving layer (the Snap ML-style
streaming tradeoff): a flush happens when ``max_batch`` requests are queued
(throughput bound) or when the OLDEST queued request has waited
``max_wait_ms`` (latency bound) — so an idle service scores a lone request
after at most one wait window, and a busy one always ships full batches.

Batch SHAPES are the flush function's concern (the service pads each flush
to a bucketed size so the jitted scorer never sees a new shape in steady
state); the batcher's concern is time: one worker thread, one condition
variable, futures for the callers. ``submit`` is thread-safe and returns a
``concurrent.futures.Future`` resolving to that request's score.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Sequence


@dataclass
class _Entry:
    request: object
    future: Future = field(default_factory=Future)
    # Monotonic, not wall: the flush deadline and the request-latency
    # metric are DURATIONS — an NTP step against time.time() here either
    # starved flushes or fired them instantly (PML004).
    enqueued_at: float = field(default_factory=time.monotonic)


def bucket_batch(n: int, max_batch: int) -> int:
    """Padded batch size for ``n`` requests: next power of two, capped at
    ``max_batch`` — a log-sized set of shapes, so the jitted scorer
    compiles O(log max_batch) programs total and then never again."""
    if n >= max_batch:
        return max_batch
    return 1 << max(0, (int(n) - 1)).bit_length()


class MicroBatcher:
    """Background flusher over a bounded-delay request queue.

    ``flush_fn(entries)`` scores ``entries`` (a list of _Entry; at most
    ``max_batch``) and returns one float per entry, in order. It runs on
    the worker thread; exceptions propagate to every future in the flush.
    """

    def __init__(
        self,
        flush_fn: Callable[[Sequence[_Entry]], Sequence[float]],
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._flush_fn = flush_fn
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait_ms) / 1e3
        self._queue: list[_Entry] = []
        self._cond = threading.Condition()
        self._running = True
        self._worker = threading.Thread(target=self._loop,
                                        name="photon-serving-batcher",
                                        daemon=True)
        self._worker.start()

    def submit(self, request) -> Future:
        entry = _Entry(request)
        with self._cond:
            if not self._running:
                raise RuntimeError("batcher is closed")
            self._queue.append(entry)
            self._cond.notify()
        return entry.future

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._queue:
                    self._cond.wait()
                if not self._running and not self._queue:
                    return
                # Wait out the remainder of the oldest entry's window
                # unless the batch is already full (or we're draining).
                deadline = self._queue[0].enqueued_at + self.max_wait
                while (self._running
                       and len(self._queue) < self.max_batch
                       and (left := deadline - time.monotonic()) > 0):
                    self._cond.wait(timeout=left)
                batch = self._queue[: self.max_batch]
                del self._queue[: len(batch)]
            try:
                scores = self._flush_fn(batch)
                for entry, score in zip(batch, scores):
                    entry.future.set_result(score)
            except Exception as exc:  # propagate to callers, keep serving
                for entry in batch:
                    if not entry.future.done():
                        entry.future.set_exception(exc)

    def close(self) -> None:
        """Drain the queue, then stop the worker (idempotent)."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._worker.is_alive():
            self._worker.join()
