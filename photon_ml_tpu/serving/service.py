"""ScoringService: the online-inference front door.

Reference parity: none — this is the layer the reference never had (its
GameScoringDriver is a batch job). One service owns the whole serving
pipeline:

    requests → micro-batcher → shape-bucketed padded batch
             → RE cache resolve (host store → LRU device cache)
             → ONE jitted scoring program → scores

The jitted program is a function of (feature matrices, offsets, cache
slots, cache tables) with fixed-effect coefficients closed over as
device-resident constants. Batch sizes are padded to power-of-two buckets
(``batcher.bucket_batch``), so the program compiles once per bucket —
O(log max_batch) programs, persisted across processes by
utils/compile_cache — and steady state NEVER recompiles (asserted by
tests and reported by dev-scripts/bench_serving.py).

Scoring semantics match offline ``cli/game_score.py`` exactly: scores are
offsets + Σ coordinate contributions, unseen entities contribute zero
(fixed-effect-only fallback), ``as_mean`` applies the task's inverse link.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs
from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.serving.batcher import (BatcherQueueFull,
                                           DeadlineExceeded, MicroBatcher,
                                           bucket_batch)
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.model_store import ResidentModelStore
from photon_ml_tpu.utils.events import (ScoringBatch, ScoringFinish,
                                        ScoringStart, default_emitter)

logger = logging.getLogger("photon_ml_tpu.serving")


@dataclasses.dataclass
class ScoringRequest:
    """One example to score.

    ``features``: shard id → dense (d,) vector, or a sparse mapping
    ``{"indices": ..., "values": ...}`` (ELL row contract: out-of-range
    indices are padding and are dropped). Shards the model never reads may
    be omitted; omitted shards contribute zero.
    ``entity_ids``: RE type → entity id — an int vocabulary row, or a raw
    key resolved through the serving vocabularies. Unknown/missing ids
    fall back to fixed-effect-only scoring.
    """

    features: dict[str, object]
    entity_ids: dict[str, object] = dataclasses.field(default_factory=dict)
    offset: float = 0.0
    uid: object = None


def requests_from_dataset(data: GameDataset) -> list[ScoringRequest]:
    """A GameDataset's rows as ScoringRequests (tests, benches, replays)."""
    out = []
    for i in range(data.num_rows):
        feats: dict[str, object] = {}
        for sid, shard in data.feature_shards.items():
            if isinstance(shard, SparseShard):
                feats[sid] = {"indices": shard.indices[i],
                              "values": shard.values[i]}
            else:
                feats[sid] = np.asarray(shard[i])
        out.append(ScoringRequest(
            features=feats,
            entity_ids={rt: int(ids[i])
                        for rt, ids in data.entity_ids.items()},
            offset=float(data.offsets[i]),
            uid=i,
        ))
    return out


class ScoringService:
    """Low-latency scoring over a resident GameModel."""

    def __init__(
        self,
        model: GameModel,
        as_mean: bool = False,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        cache_entities: int = 4096,
        store_shards: int = 8,
        entity_vocabs: Optional[dict[str, dict]] = None,
        cache_dtype: str = "float32",
        max_queue: Optional[int] = None,
        request_deadline_s: Optional[float] = 30.0,
        slo_window_s: float = 60.0,
        slo_availability: float = 0.999,
        slo_latency_ms: Optional[float] = None,
        replica_id: Optional[int] = None,
        initial_version: int = 0,
        boot_generation: Optional[int] = None,
        emitter=default_emitter,
    ):
        # Fleet membership (serving/fleet.py): the id is this replica's
        # stable index for fault addressing (`fleet.replica_flush`
        # fires with it) and for log/error attribution.
        self.replica_id = replica_id
        # Boot provenance (boot/generations.py): which generation this
        # service mapped (None = a classic npz boot); surfaced on
        # /healthz + photon_model_generation so the fleet and dashboards
        # can tell a stale replica from a current one.
        self.boot_generation = boot_generation
        # A flush's unique entities must fit the cache simultaneously
        # (model_store pins them during resolve), so the effective budget
        # is at least max_batch.
        self.store = ResidentModelStore(
            model, cache_entities=max(int(cache_entities), int(max_batch)),
            store_shards=store_shards, entity_vocabs=entity_vocabs,
            metrics_retry=self._record_store_retry,
            cache_dtype=cache_dtype, initial_version=initial_version)
        self.as_mean = bool(as_mean)
        self.max_batch = int(max_batch)
        self.metrics = ServingMetrics(slo_window_s=slo_window_s,
                                      slo_availability=slo_availability,
                                      slo_latency_ms=slo_latency_ms)
        self.emitter = emitter
        self._lock = threading.Lock()  # serializes resolve+score per flush
        self._compile_keys: set[int] = set()
        self._score_fn = self._build_score_fn()
        # Admission control default: a queue much deeper than 16 full
        # batches only buys latency nobody asked for — shed instead
        # (docs/ROBUSTNESS.md degradation ladder).
        self.max_queue = (16 * self.max_batch if max_queue is None
                          else int(max_queue))
        self.request_deadline_s = request_deadline_s
        self.batcher = MicroBatcher(
            self._flush, max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_queue=self.max_queue,
            default_deadline_s=request_deadline_s,
            on_worker_death=self._on_worker_death,
            on_deadline=self.metrics.record_deadline_exceeded,
            depth_gauge=self.metrics.queue_depth)
        self._closed = False
        emitter.emit(ScoringStart(source="serving", num_rows=None))

    def _on_worker_death(self, exc: BaseException) -> None:
        self.metrics.record_recovery()
        logger.error("scoring worker died (%s: %s) — pending requests "
                     "failed fast, worker restarted", type(exc).__name__,
                     exc)

    def _record_store_retry(self, n: int = 1) -> None:
        self.metrics.record_retry(n)

    # -- jitted scorer -----------------------------------------------------

    def _build_score_fn(self):
        fixed = tuple(self.store.fixed)
        random = tuple((st.cid, st.shard_id, st.cache_scale is not None)
                       for st in self.store.random)
        mean_fn = (losses_mod.loss_for_task(self.store.task).mean
                   if self.as_mean else None)
        # Kernel-registry resolution happens HERE, at program-build time
        # (docs/KERNELS.md): the backend choice is baked into the jitted
        # program, so steady state never re-decides — a flag flip needs
        # a service rebuild, same contract as every other config knob.
        # Flag off = no registry traffic at all; flag on but no Pallas
        # (no TPU, injected kernel.launch fault) already emitted its
        # loud KernelFallback inside resolve, and the inline XLA chain
        # below runs exactly as before.
        from photon_ml_tpu.ops import kernels
        reg = kernels.registry()
        fused = None
        self._kernel_backend = "xla"
        if random and reg.enabled("serving_score"):
            resolved = reg.resolve("serving_score",
                                   dtype=self.store.cache_dtype)
            self._kernel_backend = resolved.backend
            if resolved.backend == "pallas":
                fused = resolved

        def score(mats, offsets, slots, caches, scales):
            total = jnp.asarray(offsets)
            for _cid, sid, w in fixed:
                total = total + mats[sid] @ w
            for cid, sid, quantized in random:
                if fused is not None:
                    # One program per coordinate: gather + int8 dequant
                    # + row-dot + per-row scale, codes upcast in
                    # registers (f32 rows never hit HBM).
                    total = total + fused(
                        mats[sid], slots[cid], caches[cid],
                        scales[cid] if quantized else None)
                    continue
                rows = caches[cid][slots[cid]]
                if quantized:
                    # int8 device cache: gather the codes, accumulate
                    # the einsum in f32, dequantize with ONE per-row
                    # scale multiply (x·(s·q) = s·(x·q) — exact).
                    total = total + jnp.einsum(
                        "nd,nd->n", mats[sid],
                        rows.astype(jnp.float32)) * \
                        scales[cid][slots[cid]]
                else:
                    total = total + jnp.einsum("nd,nd->n", mats[sid],
                                               rows)
            return mean_fn(total) if mean_fn is not None else total

        return jax.jit(score)

    # -- batch assembly ----------------------------------------------------

    def _assemble(self, requests: Sequence[ScoringRequest], padded: int):
        store = self.store
        mats = {sid: np.zeros((padded, dim), np.float32)
                for sid, dim in store.shard_dims.items()}
        offsets = np.zeros(padded, np.float32)
        ids = {st.cid: np.full(len(requests), -1, np.int64)
               for st in store.random}
        for i, req in enumerate(requests):
            offsets[i] = req.offset
            for sid, feats in (req.features or {}).items():
                mat = mats.get(sid)
                if mat is None:
                    raise ValueError(
                        f"request {req.uid!r} carries unknown feature "
                        f"shard {sid!r} (model reads "
                        f"{sorted(store.shard_dims)})")
                d = mat.shape[1]
                if isinstance(feats, dict):
                    fi = np.asarray(feats["indices"], np.int64).reshape(-1)
                    fv = np.asarray(feats["values"], np.float32).reshape(-1)
                elif isinstance(feats, tuple):
                    fi = np.asarray(feats[0], np.int64).reshape(-1)
                    fv = np.asarray(feats[1], np.float32).reshape(-1)
                else:
                    v = np.asarray(feats, np.float32).reshape(-1)
                    if v.shape[0] != d:
                        raise ValueError(
                            f"request {req.uid!r} shard {sid!r}: expected "
                            f"{d} features, got {v.shape[0]}")
                    mat[i] = v
                    continue
                valid = (fi >= 0) & (fi < d)
                np.add.at(mat[i], fi[valid], fv[valid])
            ent = req.entity_ids or {}
            for st in store.random:
                ids[st.cid][i] = store.entity_row_id(
                    st.re_type, ent.get(st.re_type))
        return mats, offsets, ids

    # -- scoring paths -----------------------------------------------------

    def _score_chunk(self, requests: Sequence[ScoringRequest]):
        """Score one ≤max_batch chunk; returns ``(scores, stage_marks)``
        where the marks are the monotonic stage boundaries
        ``(assemble_start, device_start, device_end)`` — the raw material
        of per-request latency attribution (docs/SERVING.md lifecycle).
        All boundaries share ``_Entry.enqueued_at``'s clock so stage
        durations and queue waits subtract cleanly."""
        n = len(requests)
        with self._lock:
            t_a0 = time.monotonic()  # assemble: batch build + RE resolve
            padded = bucket_batch(n, self.max_batch)
            mats, offsets, ids = self._assemble(requests, padded)
            slots = self.store.resolve_slots(ids, metrics=self.metrics)
            slots_full = {
                st.cid: np.concatenate([
                    slots[st.cid],
                    np.full(padded - n, st.fallback_slot, np.int32)])
                for st in self.store.random}
            mx = obs.metrics()
            if padded not in self._compile_keys:
                self._compile_keys.add(padded)
                self.metrics.record_compile()
                if mx is not None:
                    # backend= records which kernel the program scores
                    # through (docs/KERNELS.md) — "xla" both when the
                    # flag is off and when a resolve degraded loudly.
                    mx.counter("photon_compile_cache_misses_total",
                               cache="serving_score",
                               dtype=self.store.cache_dtype,
                               backend=self._kernel_backend).inc()
            elif mx is not None:
                # The hit side of the program-cache ledger: a warm boot
                # whose warmup re-runs already-owned bucket shapes shows
                # HITS here, not silence (docs/SERVING.md "Sub-second
                # restart").
                mx.counter("photon_compile_cache_hits_total",
                           cache="serving_score",
                           dtype=self.store.cache_dtype,
                           backend=self._kernel_backend).inc()
            t_d0 = time.monotonic()  # device: dispatch + block on result
            out = self._score_fn(mats, offsets, slots_full,
                                 self.store.caches(),
                                 self.store.cache_scales())
            # pml: allow[PML019] flush-lock device sync IS the flush: one in-flight batch per device by design (docs/SERVING.md), and waiters queue in the batcher, not on this lock
            out = np.asarray(jax.block_until_ready(out))
            t_d1 = time.monotonic()
        dt = t_d1 - t_d0
        self.metrics.record_batch(n, padded, dt)
        self.emitter.emit(ScoringBatch(source="serving", rows=n,
                                       padded_rows=padded, seconds=dt))
        return out[:n], (t_a0, t_d0, t_d1)

    def warmup(self) -> int:
        """Touch every power-of-two bucket shape once so steady state
        (and the first real request) owns its compiled programs — the
        ``boot.warmup`` phase of a replica restart. Warmup rows carry no
        features and no entity ids (fallback slot only), so caches and
        scores are untouched; with the persistent compilation cache
        warm, every build here is a disk hit, not a compile. Returns the
        number of bucket shapes touched."""
        shapes = 0
        n = 1
        while n <= self.max_batch:
            self._score_chunk([ScoringRequest(features={})
                               for _ in range(n)])
            shapes += 1
            n *= 2
        # One re-run of the smallest bucket verifies the programs now
        # dispatch WARM — and moves the hit counter at boot, so a
        # restart whose cache key rotated (every shape recompiling)
        # is visible as hits staying at zero.
        self._score_chunk([ScoringRequest(features={})])
        return shapes

    def score(self, requests: Sequence[ScoringRequest]) -> np.ndarray:
        """Programmatic batch API: score now, bypassing the queue (the
        device path — bucketing, cache, metrics — is identical)."""
        scores = np.empty(len(requests), np.float32)
        for lo in range(0, len(requests), self.max_batch):
            chunk = requests[lo: lo + self.max_batch]
            scores[lo: lo + len(chunk)] = self._score_chunk(chunk)[0]
        return scores

    def submit(self, request: ScoringRequest,
               deadline_s: Optional[float] = None):
        """Queue one request through the micro-batcher; returns a Future
        resolving to its score (cross-caller batching happens here).
        Raises ``BatcherQueueFull`` when admission control sheds the
        request (counted in ``shed_total``); the returned future always
        resolves — score, error, or ``DeadlineExceeded``."""
        try:
            return self.batcher.submit(request, deadline_s=deadline_s)
        except BatcherQueueFull:
            self.metrics.record_shed()
            raise

    def _flush(self, entries):
        t_flush0 = time.monotonic()  # same clock as _Entry.enqueued_at
        try:
            # Injection sites first: a fault here is indistinguishable
            # from the scorer failing (InjectedThreadDeath, being a
            # BaseException, still sails through to the supervisor).
            # The fleet site carries the replica id as its index, so a
            # `replica_kill` spec can SIGKILL exactly one replica of a
            # fleet mid-flush (indices=[id], occurrences=[k]).
            if self.replica_id is not None:
                flt.fire(flt.sites.FLEET_REPLICA_FLUSH, index=self.replica_id)
            flt.fire(flt.sites.SERVING_FLUSH)
            scores, marks = self._score_chunk(
                [e.request for e in entries])
        except Exception:
            self.metrics.record_flush_error()
            raise
        self._attribute(entries, t_flush0, marks)
        return scores

    def _attribute(self, entries, t_flush0: float, marks) -> None:
        """Per-request latency attribution for one flush (runs on the
        batcher worker, inside the ``serving.flush`` span, BEFORE the
        futures resolve).

        Every request in the flush experienced the flush's whole
        assemble/device/respond walls plus its own queue wait, so those
        are its stages verbatim: stages sum to the request total (the
        10%-agreement contract tests and bench cross-checks rely on).
        With tracing on, each request also becomes a ``serving.request``
        span parented into this flush's span — the queue-crossing edge —
        with one child span per stage.
        """
        t_a0, t_d0, t_d1 = marks
        t_done = time.monotonic()
        assemble_s = t_d0 - t_a0
        device_s = t_d1 - t_d0
        respond_s = t_done - t_d1
        tr = obs.tracer()
        parent = tr.current() if tr is not None else None
        for e in entries:
            queue_wait_s = max(t_flush0 - e.enqueued_at, 0.0)
            total_s = t_done - e.enqueued_at
            attr = {
                "request_id": e.request_id,
                "queue_wait_ms": round(queue_wait_s * 1e3, 4),
                "assemble_ms": round(assemble_s * 1e3, 4),
                "device_score_ms": round(device_s * 1e3, 4),
                "respond_ms": round(respond_s * 1e3, 4),
                "total_ms": round(total_s * 1e3, 4),
            }
            e.attribution = attr
            # Visible to whoever holds the future, race-free: set_result
            # happens after _flush returns (the happens-before edge).
            e.future.attribution = attr
            self.metrics.record_request_latency(total_s)
            self.metrics.record_stages(queue_wait_s, assemble_s,
                                       device_s, respond_s)
            if tr is None or e.t0_epoch_ns is None:
                continue

            def _at(mono: float) -> int:
                # The entry's own (epoch, monotonic) pair anchors its
                # stage boundaries on the cross-thread trace axis.
                return e.t0_epoch_ns + int((mono - e.enqueued_at) * 1e9)

            sid = tr.record_complete(
                "serving.request", cat="serving",
                t0_epoch_ns=e.t0_epoch_ns, dur_s=total_s, parent=parent,
                crosses_queue=True, request_id=e.request_id)
            for name, mono, dur in (
                    ("serving.queue_wait", e.enqueued_at, queue_wait_s),
                    ("serving.assemble", t_a0, assemble_s),
                    ("serving.device_score", t_d0, device_s),
                    ("serving.respond", t_d1, respond_s)):
                tr.record_complete(name, cat="serving",
                                   t0_epoch_ns=_at(mono), dur_s=dur,
                                   parent=sid)

    # -- continuous publication (serving/publish.py) -----------------------

    @property
    def model_version(self) -> int:
        return self.store.version

    def apply_delta(self, delta) -> dict:
        """Zero-drop hot-swap: install one committed delta while traffic
        flows. The service lock serializes against ``_score_chunk``, so
        the in-flight flush finishes against the OLD version, the swap
        lands, and every later flush sees the NEW one — queued requests
        are never dropped and no batch mixes versions. Post-swap scores
        are bit-identical to a cold restart on the new model (the store
        re-fills invalidated cache slots from the swapped host rows
        through the unchanged resolve path)."""
        with self._lock:
            out = self.store.apply_delta(delta)
        self.metrics.record_publish_applied(out["version"])
        return out

    def apply_delta_dir(self, path: str) -> dict:
        """Load + validate + apply a committed delta directory (the
        ``POST /admin/delta`` body). Defined errors only: DeltaCorrupt
        for untrustworthy bytes, BadDelta for unservable content — the
        store never mutates on either."""
        from photon_ml_tpu.serving.publish import read_delta

        return self.apply_delta(read_delta(path))

    def apply_delta_url(self, url: str) -> dict:
        """Fetch a delta's artifacts over HTTP into a local spool, then
        apply — the remote-replica leg of ``POST /admin/delta``
        (``{"url": ...}`` body; docs/SERVING.md "Multi-host fleet").
        ``fetch_delta`` keeps the marker-last commit discipline across
        the wire and ``read_delta`` re-verifies the CRC fence on OUR
        bytes, so a torn or bit-flipped transfer raises DeltaCorrupt
        and the previously applied version stays servable."""
        from photon_ml_tpu.serving.publish import fetch_delta, read_delta

        spool = os.path.join(os.getcwd(),
                             f"delta-spool-{os.getpid()}")
        local = fetch_delta(url, spool)
        return self.apply_delta(read_delta(local))

    def rollback_to(self, version: int) -> dict:
        """Back out deltas newer than ``version`` (the canary ladder's
        auto-rollback leg), under the same flush-serialized lock as
        ``apply_delta``."""
        with self._lock:
            out = self.store.rollback_to(version)
        self.metrics.record_publish_rollback(out["version"])
        return out

    # -- lifecycle ---------------------------------------------------------

    def metrics_text(self) -> str:
        """The ``/metrics`` body: serving's own scoreboard plus — when
        process-wide observability is on — the cross-stack registry
        (transfer accounting, checkpoint/retry counters), so ONE endpoint
        exposes the whole process (docs/OBSERVABILITY.md)."""
        text = self.metrics.render_text()
        registry = obs.metrics()
        if registry is not None:
            text += registry.render_text()
        return text

    def slo_snapshot(self) -> dict:
        """The ``/slo`` body: sliding-window percentiles + error-budget
        burn, with the lifetime shed/deadline/error totals alongside so
        one payload answers both "how is the window" and "how has the
        lifetime been" (docs/SERVING.md)."""
        out = self.metrics.slo.snapshot()
        out["lifetime"] = {
            "rows_total": self.metrics.rows_total,
            "shed_total": self.metrics.shed_total,
            "deadline_exceeded_total":
                self.metrics.deadline_exceeded_total,
            "flush_errors_total": self.metrics.flush_errors_total,
            "queue_depth_peak": self.metrics.queue_depth.peak,
        }
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.batcher.close()
        self.emitter.emit(ScoringFinish(
            source="serving", num_rows=self.metrics.rows_total,
            wall_seconds=self.metrics.uptime_seconds()))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- JSON-over-HTTP front end ----------------------------------------------

def _parse_request(obj: dict) -> ScoringRequest:
    return ScoringRequest(
        features=obj.get("features") or {},
        entity_ids=obj.get("entity_ids") or {},
        offset=float(obj.get("offset", 0.0)),
        uid=obj.get("uid"),
    )


class _ServingHandler(BaseHTTPRequestHandler):
    """Minimal stdlib handler: POST /score, GET /metrics, GET /slo,
    GET /healthz.

    Each POSTed request is submitted through the micro-batcher, so
    concurrent HTTP callers coalesce into shared device batches — the
    ThreadingHTTPServer thread-per-connection model is exactly what makes
    the batcher useful here. A ``"trace": true`` key in the /score body
    opts that call into per-request latency attribution
    (queue wait / assemble / device score / respond) in the response.
    """

    service: ScoringService = None  # set by make_http_server
    result_timeout = 60.0

    def _respond(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, payload: dict) -> None:
        self._respond(code, json.dumps(payload).encode(),
                      "application/json")

    def do_GET(self):
        if self.path == "/metrics":
            self._respond(200, self.service.metrics_text().encode(),
                          "text/plain; version=0.0.4")
        elif self.path == "/slo":
            self._json(200, self.service.slo_snapshot())
        elif self.path == "/healthz":
            self._json(200, {"status": "ok",
                             "model_version":
                                 self.service.model_version,
                             "generation":
                                 self.service.boot_generation})
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def _error(self, code: int, message: str, **extra) -> None:
        """One JSON error body + one metrics increment — every failure
        leaves through here, never as an unhandled exception on the
        handler thread (which would reset the connection with no body
        and no count). ``extra`` keys (non-None) ride along in the body
        (the 503 shed body carries the observed queue depth)."""
        self.service.metrics.record_http_error(code)
        body = {"error": message}
        body.update({k: v for k, v in extra.items() if v is not None})
        self._json(code, body)

    def _admin(self, payload: dict) -> None:
        """Publication control plane (``/admin/delta``, ``/admin/
        rollback``): the fleet's canary ladder drives a replica through
        these. Errors are DEFINED and counted: 400 for a delta the
        replica refuses (corrupt bytes, unservable content, chain
        break), never a silent wrong swap."""
        from photon_ml_tpu.serving.publish import PublishError

        try:
            if self.path == "/admin/delta":
                if "url" in payload:
                    out = self.service.apply_delta_url(
                        str(payload["url"]))
                else:
                    out = self.service.apply_delta_dir(
                        str(payload["path"]))
            else:
                out = self.service.rollback_to(
                    int(payload["to_version"]))
        except PublishError as exc:
            self._error(400, str(exc),
                        model_version=self.service.model_version)
            return
        except (KeyError, TypeError, ValueError) as exc:
            self._error(400, f"malformed admin request: {exc}")
            return
        self._json(200, out)

    def do_POST(self):
        if self.path in ("/admin/delta", "/admin/rollback"):
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("admin body must be a JSON object")
            except (ValueError, TypeError) as exc:
                self._error(400, f"malformed admin request: {exc}")
                return
            self._admin(payload)
            return
        if self.path != "/score":
            self._error(404, f"unknown path {self.path}")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
            reqs = [_parse_request(o) for o in payload.get("requests", [])]
            want_trace = bool(payload.get("trace", False))
        except (ValueError, TypeError, AttributeError, KeyError) as exc:
            # Malformed JSON / wrong shapes: the CALLER's fault — 400.
            logger.warning("malformed scoring request: %s", exc)
            self._error(400, f"malformed request: {exc}")
            return
        if not reqs:
            self._error(400, "no requests")
            return
        try:
            futures = [self.service.submit(r) for r in reqs]
        except BatcherQueueFull as exc:
            # Admission control: shed with a Retry-After signal instead
            # of buffering unboundedly (shed_total counts it); the body
            # reports the observed depth so callers and dashboards see
            # HOW saturated, not just that it was.
            self._error(503, str(exc), queue_depth=exc.depth,
                        max_queue=exc.max_queue)
            return
        try:
            scores = [float(f.result(timeout=self.result_timeout))
                      for f in futures]
        except (DeadlineExceeded, TimeoutError, _FutureTimeout) as exc:
            self._error(504, f"scoring deadline exceeded: {exc}")
            return
        except Exception as exc:  # scoring/batcher error → 500 + count
            logger.exception("scoring request failed")
            self._error(500, f"scoring failed: {exc}")
            return
        body = {"scores": scores, "uids": [r.uid for r in reqs]}
        if want_trace:
            # Filled by the flush before each future resolved; reading
            # after result() is the race-free side of that edge.
            body["attribution"] = [getattr(f, "attribution", None)
                                   for f in futures]
        self._json(200, body)

    def log_message(self, fmt, *args):  # route access logs off stderr
        logger.debug("http: " + fmt, *args)


def make_http_server(service: ScoringService, host: str = "127.0.0.1",
                     port: int = 8080) -> ThreadingHTTPServer:
    """Bind (not yet serving — call ``serve_forever``). ``port=0`` picks a
    free port (tests); the bound port is ``server.server_address[1]``."""
    handler = type("BoundServingHandler", (_ServingHandler,),
                   {"service": service})
    return ThreadingHTTPServer((host, port), handler)
