"""Serving metrics: latency percentiles, throughput, batch fill, RE cache.

Reference parity: none — the reference has no online story at all (its
scoring driver is a batch job). The shape here follows standard model-server
practice (latency histograms + counters behind a text endpoint) so the
subsystem is observable from the first request: every micro-batch flush
records device latency and fill, every queued request records end-to-end
latency, and the random-effect device cache reports hit/miss/unseen/eviction
counts per coordinate.

All methods are thread-safe (one lock; the HTTP front end and the batcher
worker record concurrently).
"""

from __future__ import annotations

import threading
import time

# The latency reservoir is the cross-stack histogram of obs/metrics.py
# (photon-obs generalized this module's percentile ring into the
# process-wide registry); the name survives for serving call sites.
from photon_ml_tpu.obs.metrics import Histogram as LatencyHistogram

__all__ = ["CacheCounters", "LatencyHistogram", "ServingMetrics"]


class CacheCounters:
    """Per-coordinate random-effect device-cache counters."""

    def __init__(self):
        self.hits = 0  # rows whose entity was already device-resident
        self.misses = 0  # rows whose entity was fetched from the host store
        self.unseen = 0  # rows scored fixed-effect-only (entity unknown)
        self.evictions = 0  # LRU slots reclaimed

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def summary(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "unseen": self.unseen, "evictions": self.evictions,
                "hit_rate": self.hit_rate()}


class ServingMetrics:
    """One scoreboard per ScoringService."""

    def __init__(self):
        self._lock = threading.Lock()
        # Wall clock is for the TIMESTAMP only; uptime/throughput are
        # durations and come off the monotonic clock (an NTP step must
        # not dent the rates — PML004).
        self.started_at = time.time()
        self._started_mono = time.monotonic()
        self.request_latency = LatencyHistogram()  # submit → result
        self.batch_latency = LatencyHistogram()  # one device flush
        self.rows_total = 0
        self.padded_rows_total = 0
        self.batches_total = 0
        self.compiles_total = 0  # distinct jitted batch shapes built
        self.cache: dict[str, CacheCounters] = {}  # coordinate id → counts
        # Robustness counters (docs/ROBUSTNESS.md): every degradation is
        # observable, or the hardening is unverifiable in production.
        self.shed_total = 0  # requests rejected by admission control
        self.deadline_exceeded_total = 0  # requests expired in the queue
        self.flush_errors_total = 0  # batches whose flush raised
        self.retries_total = 0  # transient host-store fetch retries
        self.recoveries_total = 0  # batcher worker deaths recovered from
        self.http_errors_total: dict[int, int] = {}  # status code → count

    def coordinate(self, cid: str) -> CacheCounters:
        with self._lock:
            return self.cache.setdefault(cid, CacheCounters())

    def record_batch(self, rows: int, padded_rows: int,
                     seconds: float) -> None:
        with self._lock:
            self.rows_total += rows
            self.padded_rows_total += padded_rows
            self.batches_total += 1
            self.batch_latency.record(seconds)

    def record_request_latency(self, seconds: float) -> None:
        with self._lock:
            self.request_latency.record(seconds)

    def record_compile(self) -> None:
        with self._lock:
            self.compiles_total += 1

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self.shed_total += n

    def record_deadline_exceeded(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_exceeded_total += n

    def record_flush_error(self) -> None:
        with self._lock:
            self.flush_errors_total += 1

    def record_retry(self, n: int = 1) -> None:
        with self._lock:
            self.retries_total += n

    def record_recovery(self) -> None:
        with self._lock:
            self.recoveries_total += 1

    def record_http_error(self, code: int) -> None:
        with self._lock:
            self.http_errors_total[code] = \
                self.http_errors_total.get(code, 0) + 1

    def record_cache(self, cid: str, hits: int = 0, misses: int = 0,
                     unseen: int = 0, evictions: int = 0) -> None:
        c = self.coordinate(cid)
        with self._lock:
            c.hits += hits
            c.misses += misses
            c.unseen += unseen
            c.evictions += evictions

    # -- views -------------------------------------------------------------

    def fill_ratio(self) -> float:
        return (self.rows_total / self.padded_rows_total
                if self.padded_rows_total else 0.0)

    def uptime_seconds(self) -> float:
        return time.monotonic() - self._started_mono

    def throughput_rows_per_sec(self) -> float:
        dt = self.uptime_seconds()
        return self.rows_total / dt if dt > 0 else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_seconds": self.uptime_seconds(),
                "rows_total": self.rows_total,
                "batches_total": self.batches_total,
                "padded_rows_total": self.padded_rows_total,
                "batch_fill_ratio": self.fill_ratio(),
                "throughput_rows_per_sec": self.throughput_rows_per_sec(),
                "compiles_total": self.compiles_total,
                "shed_total": self.shed_total,
                "deadline_exceeded_total": self.deadline_exceeded_total,
                "flush_errors_total": self.flush_errors_total,
                "retries_total": self.retries_total,
                "recoveries_total": self.recoveries_total,
                "http_errors_total": dict(self.http_errors_total),
                "request_latency": self.request_latency.summary(),
                "batch_latency": self.batch_latency.summary(),
                "re_cache": {cid: c.summary()
                             for cid, c in self.cache.items()},
            }

    def render_text(self) -> str:
        """Prometheus-style text exposition (the /metrics endpoint body)."""
        s = self.snapshot()
        lines = [
            f"photon_serving_uptime_seconds {s['uptime_seconds']:.3f}",
            f"photon_serving_rows_total {s['rows_total']}",
            f"photon_serving_batches_total {s['batches_total']}",
            f"photon_serving_batch_fill_ratio {s['batch_fill_ratio']:.6f}",
            f"photon_serving_throughput_rows_per_sec "
            f"{s['throughput_rows_per_sec']:.3f}",
            f"photon_serving_compiles_total {s['compiles_total']}",
            f"photon_serving_shed_total {s['shed_total']}",
            f"photon_serving_deadline_exceeded_total "
            f"{s['deadline_exceeded_total']}",
            f"photon_serving_flush_errors_total {s['flush_errors_total']}",
            f"photon_serving_retries_total {s['retries_total']}",
            f"photon_serving_recoveries_total {s['recoveries_total']}",
        ]
        for code, n in sorted(s["http_errors_total"].items()):
            lines.append(
                f"photon_serving_http_errors_total{{code=\"{code}\"}} {n}")
        for name, h in (("request", s["request_latency"]),
                        ("batch", s["batch_latency"])):
            lines.append(f"photon_serving_{name}_latency_count {h['count']}")
            for q in ("p50", "p95", "p99"):
                lines.append(f"photon_serving_{name}_latency_ms"
                             f"{{quantile=\"{q}\"}} {h[q + '_ms']:.4f}")
        for cid, c in s["re_cache"].items():
            for k in ("hits", "misses", "unseen", "evictions"):
                lines.append(
                    f"photon_serving_re_cache_{k}{{coordinate=\"{cid}\"}} "
                    f"{c[k]}")
            lines.append(
                f"photon_serving_re_cache_hit_rate{{coordinate=\"{cid}\"}} "
                f"{c['hit_rate']:.6f}")
        return "\n".join(lines) + "\n"
