"""Serving metrics: latency percentiles, throughput, batch fill, RE cache,
per-stage latency attribution, and sliding-window SLO accounting.

Reference parity: none — the reference has no online story at all (its
scoring driver is a batch job). The shape here follows standard model-server
practice (latency histograms + counters behind a text endpoint) so the
subsystem is observable from the first request: every micro-batch flush
records device latency and fill, every queued request records end-to-end
latency AND its stage split (queue wait / assemble / device score /
respond — docs/SERVING.md request lifecycle), and the random-effect device
cache reports hit/miss/unseen/eviction counts per coordinate.

The SLO layer (:class:`SLOTracker`) is the rolling-window view the
lifetime histograms cannot give: lifetime p99 over a long uptime hides a
bad last five minutes, and error-budget burn is only meaningful over a
window. It feeds the ``/slo`` endpoint and the ``photon_serving_slo_*``
exposition lines.

All methods are thread-safe (one lock; the HTTP front end and the batcher
worker record concurrently).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

import numpy as np

# The latency reservoir is the cross-stack histogram of obs/metrics.py
# (photon-obs generalized this module's percentile ring into the
# process-wide registry); the name survives for serving call sites.
from photon_ml_tpu.obs.metrics import Gauge
from photon_ml_tpu.obs.metrics import Histogram as LatencyHistogram

__all__ = ["CacheCounters", "LatencyHistogram", "STAGES", "ShardHeat",
           "SLOTracker", "ServingMetrics"]

# The request lifecycle stages (docs/SERVING.md): a queued request's
# end-to-end latency decomposes into exactly these four intervals.
STAGES = ("queue_wait", "assemble", "device_score", "respond")


class SLOTracker:
    """Sliding-window latency percentiles + error-budget accounting.

    ``record_ok(latency_s)`` is one successfully answered request;
    ``record_bad(kind)`` is one request the service failed its users on —
    the kinds are the serving degradation ladder: ``shed`` (admission
    control, HTTP 503), ``deadline`` (expired in the queue, HTTP 504),
    ``error`` (scoring failure, HTTP 5xx other than 503/504 — those two
    are already counted at their sources). A request slower than
    ``latency_objective_ms`` (when set) burns budget too, as ``slow``.

    The error budget is the standard SRE formulation: with availability
    objective ``a`` over the window, the budget is a ``1 - a`` fraction
    of requests; ``budget_burn_rate`` is (bad fraction) / (1 - a) — 1.0
    means burning exactly the sustainable rate, >1 means the window is
    eating future budget.

    All clocks are monotonic (PML004); the window prunes lazily on
    record/snapshot. ``max_samples`` bounds memory under overload —
    beyond it the OLDEST samples drop first (the window result is then
    computed over the most recent ``max_samples`` observations, which is
    also the regime where percentiles are most stable).
    """

    def __init__(self, window_s: float = 60.0,
                 availability_objective: float = 0.999,
                 latency_objective_ms: Optional[float] = None,
                 max_samples: int = 65536):
        if not 0.0 < availability_objective < 1.0:
            raise ValueError(
                f"availability objective must be in (0, 1), got "
                f"{availability_objective}")
        self._lock = threading.Lock()
        self.window_s = float(window_s)
        self.availability_objective = float(availability_objective)
        self.latency_objective_ms = (
            None if latency_objective_ms is None
            else float(latency_objective_ms))
        # (monotonic_t, latency_s) / (monotonic_t, kind)
        self._ok: collections.deque = collections.deque(maxlen=max_samples)
        self._bad: collections.deque = collections.deque(maxlen=max_samples)

    def record_ok(self, latency_s: float,
                  now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._ok.append((now, float(latency_s)))
            if (self.latency_objective_ms is not None
                    and latency_s * 1e3 > self.latency_objective_ms):
                self._bad.append((now, "slow"))
            self._prune_locked(now)

    def record_bad(self, kind: str, n: int = 1,
                   now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            for _ in range(int(n)):
                self._bad.append((now, kind))
            self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        for q in (self._ok, self._bad):
            while q and q[0][0] < horizon:
                q.popleft()

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._prune_locked(now)
            lats = [v for _, v in self._ok]
            bad = collections.Counter(k for _, k in self._bad)
        ok_n, bad_n = len(lats), sum(bad.values())
        total = ok_n + bad_n
        # "slow" requests were ALSO recorded ok (they completed); they
        # burn budget without changing the request count.
        total -= bad.get("slow", 0)
        bad_frac = bad_n / total if total else 0.0
        budget = 1.0 - self.availability_objective
        if lats:
            arr = np.asarray(lats)
            p50, p95, p99 = (float(np.percentile(arr, p))
                             for p in (50, 95, 99))
        else:
            p50 = p95 = p99 = 0.0
        return {
            "window_seconds": self.window_s,
            "availability_objective": self.availability_objective,
            "latency_objective_ms": self.latency_objective_ms,
            "requests_in_window": total,
            "ok_in_window": ok_n,
            "bad_in_window": bad_n,
            "bad_by_kind": dict(bad),
            "availability": 1.0 - bad_frac,
            "error_budget_fraction": budget,
            "budget_burn_rate": bad_frac / budget,
            "p50_ms": p50 * 1e3,
            "p95_ms": p95 * 1e3,
            "p99_ms": p99 * 1e3,
        }


class ShardHeat:
    """Per-shard sliding-window load accounting — the HEAT MODEL the
    elastic control loop acts on (serving/elastic.py; docs/SERVING.md
    "Elastic fleet").

    Each routed request records against its shard: a request count, the
    entity it named (distinct-entity cardinality separates "one hot
    user" — unsplittable — from "a hot shard of many users", the case
    splitting fixes), and later its observed service seconds (the
    queue/stage contribution: a shard whose requests take longer is
    hotter at equal QPS). The window prunes lazily, the same discipline
    as :class:`SLOTracker`; ``heat(shard)`` is the window request count
    weighted by the shard's mean service seconds — a pure function of
    the window, so two controllers reading the same tape reach the same
    decisions (the drills replay).

    Thread-safe: router/handler threads record, the controller thread
    snapshots.
    """

    def __init__(self, window_s: float = 30.0, max_samples: int = 65536):
        self._lock = threading.Lock()
        self.window_s = float(window_s)
        # (monotonic_t, shard, entity_key | None, seconds)
        self._events: collections.deque = collections.deque(
            maxlen=max_samples)

    def record(self, shard: int, entity=None, seconds: float = 0.0,
               now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((now, int(shard), entity,
                                 float(seconds), True))
            self._prune_locked(now)

    def record_seconds(self, shard: int, seconds: float,
                       now: Optional[float] = None) -> None:
        """Attribute observed service seconds to ``shard`` without
        counting another request (the post-response half)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((now, int(shard), None,
                                 float(seconds), False))
            self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def snapshot(self, now: Optional[float] = None,
                 resolver=None) -> dict[int, dict]:
        """{shard: {requests, entities, seconds, heat}} over the
        window. ``heat`` = requests × (1 + mean service seconds): a
        rate signal with a queue-contribution weight.

        ``resolver(entity_key) -> shard`` re-resolves each
        entity-carrying event through the CURRENT shard map: after a
        split, the window's evidence follows the children instead of
        pinning the parent's residue — without this, stale pre-split
        events keep the parent looking multi-entity-hot for a full
        window and the controller re-splits it on evidence that no
        longer routes there (the repeated-split bug the live drill
        caught). Events without an entity key keep their recorded
        shard (misattribution bounded by the window)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._prune_locked(now)
            events = list(self._events)
        out: dict[int, dict] = {}
        ents: dict[int, set] = {}
        for _, shard, entity, seconds, is_request in events:
            if resolver is not None and entity is not None:
                shard = resolver(entity)
            row = out.setdefault(shard, {"requests": 0, "entities": 0,
                                         "seconds": 0.0, "heat": 0.0})
            if is_request:
                row["requests"] += 1
                if entity is not None:
                    ents.setdefault(shard, set()).add(entity)
            row["seconds"] += seconds
        for shard, row in out.items():
            row["entities"] = len(ents.get(shard, ()))
            n = max(row["requests"], 1)
            row["heat"] = row["requests"] * (1.0 + row["seconds"] / n)
        return out

    def total_heat(self, now: Optional[float] = None) -> float:
        return sum(r["heat"] for r in self.snapshot(now).values())


class CacheCounters:
    """Per-coordinate random-effect device-cache counters."""

    def __init__(self):
        self.hits = 0  # rows whose entity was already device-resident
        self.misses = 0  # rows whose entity was fetched from the host store
        self.unseen = 0  # rows scored fixed-effect-only (entity unknown)
        self.evictions = 0  # LRU slots reclaimed

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def summary(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "unseen": self.unseen, "evictions": self.evictions,
                "hit_rate": self.hit_rate()}


class ServingMetrics:
    """One scoreboard per ScoringService."""

    def __init__(self, slo_window_s: float = 60.0,
                 slo_availability: float = 0.999,
                 slo_latency_ms: Optional[float] = None):
        self._lock = threading.Lock()
        # Wall clock is for the TIMESTAMP only; uptime/throughput are
        # durations and come off the monotonic clock (an NTP step must
        # not dent the rates — PML004).
        self.started_at = time.time()
        self._started_mono = time.monotonic()
        self.request_latency = LatencyHistogram()  # submit → result
        self.batch_latency = LatencyHistogram()  # one device flush
        self.rows_total = 0
        self.padded_rows_total = 0
        self.batches_total = 0
        self.compiles_total = 0  # distinct jitted batch shapes built
        self.cache: dict[str, CacheCounters] = {}  # coordinate id → counts
        # Robustness counters (docs/ROBUSTNESS.md): every degradation is
        # observable, or the hardening is unverifiable in production.
        self.shed_total = 0  # requests rejected by admission control
        self.deadline_exceeded_total = 0  # requests expired in the queue
        self.flush_errors_total = 0  # batches whose flush raised
        self.retries_total = 0  # transient host-store fetch retries
        self.recoveries_total = 0  # batcher worker deaths recovered from
        self.http_errors_total: dict[int, int] = {}  # status code → count
        # Request-stage attribution (docs/SERVING.md lifecycle): each
        # COMPLETED queued request adds its own queue wait plus the full
        # assemble/device/respond walls of the flush that carried it, so
        # sum(stages) tracks sum(request_latency) — the cross-check
        # bench_serving.py holds the bench lines to.
        self.stage_seconds_total: dict[str, float] = {
            s: 0.0 for s in STAGES}
        self.stage_requests_total = 0  # requests attributed above
        # Queue depth: observed on every batcher queue transition; the
        # peak is the admission-control headroom number (ISSUE 8).
        self.queue_depth = Gauge()
        # Continuous publication (serving/publish.py): which model
        # version this replica serves, and how it got there.
        self.model_version = 0
        self.deltas_applied_total = 0
        self.rollbacks_total = 0
        self.slo = SLOTracker(window_s=slo_window_s,
                              availability_objective=slo_availability,
                              latency_objective_ms=slo_latency_ms)

    def coordinate(self, cid: str) -> CacheCounters:
        with self._lock:
            return self.cache.setdefault(cid, CacheCounters())

    def record_batch(self, rows: int, padded_rows: int,
                     seconds: float) -> None:
        with self._lock:
            self.rows_total += rows
            self.padded_rows_total += padded_rows
            self.batches_total += 1
            self.batch_latency.record(seconds)

    def record_request_latency(self, seconds: float) -> None:
        with self._lock:
            self.request_latency.record(seconds)
        self.slo.record_ok(seconds)

    def record_stages(self, queue_wait_s: float, assemble_s: float,
                      device_s: float, respond_s: float) -> None:
        """One completed queued request's stage split (seconds)."""
        with self._lock:
            st = self.stage_seconds_total
            st["queue_wait"] += queue_wait_s
            st["assemble"] += assemble_s
            st["device_score"] += device_s
            st["respond"] += respond_s
            self.stage_requests_total += 1

    def record_compile(self) -> None:
        with self._lock:
            self.compiles_total += 1

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self.shed_total += n
        self.slo.record_bad("shed", n)

    def record_deadline_exceeded(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_exceeded_total += n
        self.slo.record_bad("deadline", n)

    def record_flush_error(self) -> None:
        with self._lock:
            self.flush_errors_total += 1

    def record_retry(self, n: int = 1) -> None:
        with self._lock:
            self.retries_total += n

    def record_recovery(self) -> None:
        with self._lock:
            self.recoveries_total += 1

    def record_publish_applied(self, version: int) -> None:
        with self._lock:
            self.model_version = int(version)
            self.deltas_applied_total += 1

    def record_publish_rollback(self, version: int) -> None:
        with self._lock:
            self.model_version = int(version)
            self.rollbacks_total += 1

    def record_http_error(self, code: int) -> None:
        with self._lock:
            self.http_errors_total[code] = \
                self.http_errors_total.get(code, 0) + 1
        # 5xx burns error budget; 503/504 are excluded here because shed
        # and deadline expiry already burned it at their sources (and the
        # programmatic paths must count them without an HTTP front end).
        if code >= 500 and code not in (503, 504):
            self.slo.record_bad("error")

    def record_cache(self, cid: str, hits: int = 0, misses: int = 0,
                     unseen: int = 0, evictions: int = 0) -> None:
        c = self.coordinate(cid)
        with self._lock:
            c.hits += hits
            c.misses += misses
            c.unseen += unseen
            c.evictions += evictions

    # -- views -------------------------------------------------------------

    def fill_ratio(self) -> float:
        return (self.rows_total / self.padded_rows_total
                if self.padded_rows_total else 0.0)

    def uptime_seconds(self) -> float:
        return time.monotonic() - self._started_mono

    def throughput_rows_per_sec(self) -> float:
        dt = self.uptime_seconds()
        return self.rows_total / dt if dt > 0 else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_seconds": self.uptime_seconds(),
                "rows_total": self.rows_total,
                "batches_total": self.batches_total,
                "padded_rows_total": self.padded_rows_total,
                "batch_fill_ratio": self.fill_ratio(),
                "throughput_rows_per_sec": self.throughput_rows_per_sec(),
                "compiles_total": self.compiles_total,
                "shed_total": self.shed_total,
                "deadline_exceeded_total": self.deadline_exceeded_total,
                "flush_errors_total": self.flush_errors_total,
                "retries_total": self.retries_total,
                "recoveries_total": self.recoveries_total,
                "http_errors_total": dict(self.http_errors_total),
                "model_version": self.model_version,
                "deltas_applied_total": self.deltas_applied_total,
                "rollbacks_total": self.rollbacks_total,
                "request_latency": self.request_latency.summary(),
                "request_latency_sum_seconds": \
                    self.request_latency.values()["sum"],
                "batch_latency": self.batch_latency.summary(),
                "stage_seconds_total": dict(self.stage_seconds_total),
                "stage_requests_total": self.stage_requests_total,
                "queue_depth": self.queue_depth.value,
                "queue_depth_peak": self.queue_depth.peak,
                "re_cache": {cid: c.summary()
                             for cid, c in self.cache.items()},
            }

    def render_text(self) -> str:
        """Prometheus-style text exposition (the /metrics endpoint body)."""
        s = self.snapshot()
        lines = [
            f"photon_serving_uptime_seconds {s['uptime_seconds']:.3f}",
            f"photon_serving_rows_total {s['rows_total']}",
            f"photon_serving_batches_total {s['batches_total']}",
            f"photon_serving_batch_fill_ratio {s['batch_fill_ratio']:.6f}",
            f"photon_serving_throughput_rows_per_sec "
            f"{s['throughput_rows_per_sec']:.3f}",
            f"photon_serving_compiles_total {s['compiles_total']}",
            f"photon_serving_shed_total {s['shed_total']}",
            f"photon_serving_deadline_exceeded_total "
            f"{s['deadline_exceeded_total']}",
            f"photon_serving_flush_errors_total {s['flush_errors_total']}",
            f"photon_serving_retries_total {s['retries_total']}",
            f"photon_serving_recoveries_total {s['recoveries_total']}",
            f"photon_serving_model_version {s['model_version']}",
            f"photon_serving_deltas_applied_total "
            f"{s['deltas_applied_total']}",
            f"photon_serving_rollbacks_total {s['rollbacks_total']}",
        ]
        lines.append(f"photon_serving_queue_depth {s['queue_depth']:g}")
        lines.append(
            f"photon_serving_queue_depth_peak {s['queue_depth_peak']:g}")
        for stage in STAGES:
            lines.append(
                f"photon_serving_stage_seconds_total{{stage=\"{stage}\"}} "
                f"{s['stage_seconds_total'][stage]:.6f}")
        for code, n in sorted(s["http_errors_total"].items()):
            lines.append(
                f"photon_serving_http_errors_total{{code=\"{code}\"}} {n}")
        slo = self.slo.snapshot()
        lines.append(f"photon_serving_slo_window_seconds "
                     f"{slo['window_seconds']:g}")
        lines.append(f"photon_serving_slo_availability_objective "
                     f"{slo['availability_objective']:g}")
        lines.append(f"photon_serving_slo_requests_in_window "
                     f"{slo['requests_in_window']}")
        lines.append(f"photon_serving_slo_bad_in_window "
                     f"{slo['bad_in_window']}")
        for kind, n in sorted(slo["bad_by_kind"].items()):
            lines.append(f"photon_serving_slo_bad_in_window_by_kind"
                         f"{{kind=\"{kind}\"}} {n}")
        lines.append(f"photon_serving_slo_availability "
                     f"{slo['availability']:.6f}")
        lines.append(f"photon_serving_slo_budget_burn_rate "
                     f"{slo['budget_burn_rate']:.6f}")
        for q in ("p50", "p95", "p99"):
            lines.append(f"photon_serving_slo_latency_ms"
                         f"{{quantile=\"{q}\"}} {slo[q + '_ms']:.4f}")
        for name, h in (("request", s["request_latency"]),
                        ("batch", s["batch_latency"])):
            lines.append(f"photon_serving_{name}_latency_count {h['count']}")
            for q in ("p50", "p95", "p99"):
                lines.append(f"photon_serving_{name}_latency_ms"
                             f"{{quantile=\"{q}\"}} {h[q + '_ms']:.4f}")
        for cid, c in s["re_cache"].items():
            for k in ("hits", "misses", "unseen", "evictions"):
                lines.append(
                    f"photon_serving_re_cache_{k}{{coordinate=\"{cid}\"}} "
                    f"{c[k]}")
            lines.append(
                f"photon_serving_re_cache_hit_rate{{coordinate=\"{cid}\"}} "
                f"{c['hit_rate']:.6f}")
        return "\n".join(lines) + "\n"
