"""Replica supervision: spawn, probe, and restart scoring replicas.

The fleet (serving/fleet.py) scales the single-process ``ScoringService``
horizontally: N OS-process replicas, each a full ``photon-game-serve``
server over the same model. This module owns their LIFECYCLE — the
process-level analogue of the micro-batcher's supervised worker thread
(PR 4's ``BatcherDied`` discipline, lifted one level):

- **Spawn.** How an incarnation starts is the TRANSPORT's business
  (fabric/transport.py): ``LocalTransport`` is the original subprocess
  mechanism verbatim — ``spawn``-style children (fresh interpreters:
  the parent holds live XLA runtime threads and forking them is
  undefined, the utils/workers.py rule), output to FILES never pipes
  (XLA's CPU warnings alone can overflow a 64 KB pipe buffer, and an
  undrained pipe blocks the child mid-request — the
  tests/test_multiprocess.py lesson), the bound port traveling back
  through a generation-named ready-file (``--ready-file`` in
  cli/serve.py — no port-allocation race). ``RemoteTransport`` starts
  the same replica on another machine via its agent and hands back an
  address. The LADDER below neither knows nor cares which.
- **Probe.** A monitor thread polls each replica: transport-level
  liveness (``proc.poll()`` locally; the agent's view remotely — with
  ``None`` = "cannot see the process layer", which is NOT a death),
  then GET ``/healthz`` (explicit timeout — PML011) for liveness. A
  replica whose last good probe is older than ``heartbeat_deadline_s``
  is DECLARED dead even if the process lingers (a wedged replica is
  dead for routing purposes; the lingering process is SIGKILLed so it
  cannot answer a stale hedge later).
- **Recover.** Death fires ``on_death(replica_id)`` synchronously on the
  monitor thread — the fleet re-homes the replica's shards there, inside
  the detection-to-recovery window the rehome deadline measures — then
  the supervisor restarts the replica (bounded ``max_restarts``,
  deterministic backoff) and fires ``on_recovered(replica_id)`` once the
  newcomer answers ``/healthz``. Under a ``RemoteTransport``, a restart
  whose home MACHINE is dead fails over to the next machine — the
  whole-group-death drill's bounded cross-machine re-home.

Every blocking network call in this module carries an explicit timeout
(lint rule PML011 mechanizes that for router/supervisor code).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import subprocess
import threading
import time
import urllib.request
from typing import Callable, Optional, Sequence

from photon_ml_tpu import faults as flt
from photon_ml_tpu.fabric.transport import (  # noqa: F401  (re-export)
    LocalTransport, ReplicaStartupError, Transport)

logger = logging.getLogger("photon_ml_tpu.serving.fleet")

# Replica states (the /healthz fleet view renders these verbatim).
STARTING = "starting"
UP = "up"
DOWN = "down"
RESTARTING = "restarting"
FAILED = "failed"  # restart budget exhausted — stays down, fleet degraded
RETIRED = "retired"  # scaled down deliberately — not a failure state


@dataclasses.dataclass
class ReplicaHandle:
    """One supervised replica process (mutable; guarded by the
    supervisor's lock for state transitions)."""

    replica_id: int
    proc: Optional[subprocess.Popen] = None  # LocalTransport only
    host: str = "127.0.0.1"
    port: int = 0
    state: str = STARTING
    last_ok: float = 0.0  # monotonic instant of the last good probe
    restarts: int = 0
    generation: int = 0  # bumped per spawn — ready files never reused
    last_restart_at: float = 0.0  # monotonic instant of the last restart
    log_path: str = ""
    boot_seconds: float = 0.0  # spawn → first healthy probe, last (re)start
    spawned_at: float = 0.0  # monotonic instant of the last _spawn
    machine: str = ""  # placement (agent base URL; '' when local)

    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"


def _probe_healthz(url: str, timeout_s: float) -> dict:
    """GET ``url``/healthz with an explicit timeout; raises on any
    failure (connection refused/reset, HTTP error, bad JSON)."""
    with urllib.request.urlopen(f"{url}/healthz",
                                timeout=timeout_s) as resp:
        return json.loads(resp.read())


class ReplicaSupervisor:
    """Spawns and babysits ``num_replicas`` scoring-replica processes.

    ``make_argv(replica_id, ready_file)`` returns the child's argv (the
    fleet builds it around ``python -m photon_ml_tpu.cli.serve``); the
    supervisor owns ready-file handshakes, health probing, death
    declaration, and bounded restart. ``on_death`` / ``on_recovered``
    run on the monitor thread — re-homing happens inside ``on_death`` so
    the rehome clock starts at detection.

    ``transport`` picks the replica-start MECHANISM (default: a
    ``LocalTransport`` over ``make_argv``/``workdir``, which is the
    original in-process-supervised subprocess behavior verbatim).
    """

    def __init__(
        self,
        make_argv: Callable[[int, str], Sequence[str]],
        num_replicas: int,
        workdir: str,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 1.0,
        heartbeat_deadline_s: float = 2.0,
        start_timeout_s: float = 120.0,
        max_restarts: int = 3,
        restart_backoff_s: float = 0.1,
        backoff_reset_s: float = 60.0,
        on_death: Optional[Callable[[int], None]] = None,
        on_recovered: Optional[Callable[[int], None]] = None,
        transport: Optional[Transport] = None,
    ):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, "
                             f"got {num_replicas}")
        self._make_argv = make_argv
        self.workdir = workdir
        self.transport = (transport if transport is not None
                          else LocalTransport(make_argv, workdir))
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.heartbeat_deadline_s = float(heartbeat_deadline_s)
        self.start_timeout_s = float(start_timeout_s)
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        # The backoff-ladder amnesty (ISSUE 15 satellite): a replica
        # healthy this long after a restart earns its ladder back — a
        # crash-once-then-healthy-for-hours replica must not pay the
        # escalated backoff (and restart budget) on its NEXT death.
        self.backoff_reset_s = float(backoff_reset_s)
        self._on_death = on_death
        self._on_recovered = on_recovered
        self.replicas = [ReplicaHandle(replica_id=i)
                         for i in range(num_replicas)]
        self._lock = threading.Lock()
        self._running = False
        self._monitor: Optional[threading.Thread] = None

    # -- spawn / handshake ---------------------------------------------------

    def _spawn(self, handle: ReplicaHandle) -> None:
        # Generation, not restart count, names the ready file: the
        # backoff-reset amnesty rewinds `restarts`, and a rewound name
        # could collide with a DEAD incarnation's file.
        handle.generation += 1
        handle.state = STARTING
        handle.spawned_at = time.monotonic()
        self.transport.spawn(handle)

    def _await_ready(self, handle: ReplicaHandle) -> None:
        """Wait for the transport's address handshake, then a first
        good probe. The spawn→healthy wall lands in
        ``handle.boot_seconds`` — the replica-restart tail photon-boot
        attacks, measured where the fleet actually waits for it
        (``bench_serving.py --restart`` reads it back as
        ``photon_fleet_replica_boot_seconds``)."""
        rid = handle.replica_id
        t_spawn = handle.spawned_at or time.monotonic()
        deadline = time.monotonic() + self.start_timeout_s
        host, port = self.transport.await_ready(handle, deadline)
        handle.host = host
        handle.port = int(port)
        while time.monotonic() < deadline:
            try:
                _probe_healthz(handle.base_url(), self.probe_timeout_s)
                with self._lock:
                    handle.state = UP
                    handle.last_ok = time.monotonic()
                    handle.boot_seconds = handle.last_ok - t_spawn
                logger.info("replica %d healthy at %s (boot %.3fs)", rid,
                            handle.base_url(), handle.boot_seconds)
                return
            except (OSError, ValueError):
                time.sleep(0.05)
        raise ReplicaStartupError(
            f"replica {rid} bound {handle.base_url()} but never answered "
            f"/healthz within {self.start_timeout_s}s")

    def start(self) -> None:
        """Spawn every replica and wait until all answer /healthz."""
        os.makedirs(self.workdir, exist_ok=True)
        for handle in self.replicas:
            self._spawn(handle)
        try:
            for handle in self.replicas:
                self._await_ready(handle)
        except ReplicaStartupError:
            self.stop()
            raise
        self._running = True
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="photon-fleet-monitor",
            daemon=True)
        self._monitor.start()

    # -- elastic scale (docs/SERVING.md "Elastic fleet") ---------------------

    def add_replica(self) -> int:
        """Spawn ONE more supervised replica (the scale-up leg): next
        integer id, full spawn → ready-file → healthy handshake before
        it is visible to routing. Returns the new replica id; raises
        ``ReplicaStartupError`` (and reaps the half-started process) on
        failure — the fleet's map never learns about a replica that
        did not reach healthy."""
        handle = ReplicaHandle(replica_id=len(self.replicas))
        self._spawn(handle)
        try:
            self._await_ready(handle)
        except ReplicaStartupError:
            self.transport.kill(handle)
            raise
        with self._lock:
            self.replicas.append(handle)
        logger.info("replica %d scaled up (fleet now %d)",
                    handle.replica_id, len(self.replicas))
        return handle.replica_id

    def retire(self, replica_id: int) -> None:
        """Retire a DRAINED replica (the scale-down leg): mark it
        RETIRED first — the monitor never restarts a retired replica —
        then terminate the process. Deliberate, not a failure: no
        on_death fires, no restart follows."""
        handle = self.replicas[replica_id]
        with self._lock:
            handle.state = RETIRED
        self.transport.terminate(handle, timeout_s=10.0)
        logger.info("replica %d retired", replica_id)

    def kill_replica(self, replica_id: int) -> None:
        """Hard-kill a replica's PROCESS without touching its state —
        the chaos-drill seam (fleet ``/admin/kill``): the monitor must
        DISCOVER the death through its own probes, so detection latency
        stays in the measured rehome window."""
        self.transport.kill(self.replicas[replica_id])

    # -- monitoring ----------------------------------------------------------

    def _probe_once(self, handle: ReplicaHandle) -> bool:
        """One liveness check; True = the replica looked alive."""
        if self.transport.alive(handle) is False:
            return False  # positively gone; None (can't see) still probes
        try:
            # Injection seam: a `partition` spec here models the
            # monitor losing sight of a replica (probes dropped while
            # the replica itself is fine) — the false-positive death
            # the heartbeat deadline turns into a defined re-home.
            flt.fire(flt.sites.FLEET_PROBE, index=handle.replica_id)
            _probe_healthz(handle.base_url(), self.probe_timeout_s)
            return True
        except (OSError, ValueError):
            return False

    def maybe_reset_backoff(self, handle: ReplicaHandle,
                            now: Optional[float] = None) -> bool:
        """Reset a replica's restart ladder after ``backoff_reset_s``
        of healthy uptime since its last restart; True = reset
        happened. Pure bookkeeping — callable from tests directly."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if (handle.state == UP and handle.restarts > 0
                    and handle.last_restart_at > 0.0
                    and now - handle.last_restart_at
                    >= self.backoff_reset_s):
                logger.info(
                    "replica %d healthy %.0fs since its last restart — "
                    "resetting its backoff ladder (%d restart(s) "
                    "forgiven)", handle.replica_id,
                    now - handle.last_restart_at, handle.restarts)
                handle.restarts = 0
                handle.last_restart_at = 0.0
                return True
        return False

    def _monitor_loop(self) -> None:
        while self._running:
            for handle in list(self.replicas):
                if not self._running:
                    return
                if handle.state not in (UP,):
                    continue
                now = time.monotonic()
                if self._probe_once(handle):
                    with self._lock:
                        handle.last_ok = now
                    self.maybe_reset_backoff(handle, now)
                elif (self.transport.alive(handle) is False
                      or now - handle.last_ok
                      >= self.heartbeat_deadline_s):
                    # Positive process death, or /healthz silence past
                    # the deadline. An UNKNOWN process layer (remote
                    # agent unreachable — fabric.heartbeat partition)
                    # deliberately does NOT short-circuit to death.
                    self._handle_death(handle)
            time.sleep(self.probe_interval_s)

    def _handle_death(self, handle: ReplicaHandle) -> None:
        rid = handle.replica_id
        with self._lock:
            if handle.state != UP:
                return
            handle.state = DOWN
        gone = self.transport.alive(handle) is False
        where = self.transport.describe(handle)
        logger.error("replica %d%s declared dead (%s; last good probe "
                     "%.2fs ago)", rid, f" on {where}" if where else "",
                     "process exited" if gone else "heartbeat deadline",
                     time.monotonic() - handle.last_ok)
        # A wedged-but-alive process must not answer a stale request
        # after its shards re-home — kill it before announcing death.
        if not gone:
            self.transport.kill(handle)
        if self._on_death is not None:
            try:
                self._on_death(rid)
            except Exception:
                logger.exception("on_death(%d) callback failed", rid)
        self._restart(handle)

    def _restart(self, handle: ReplicaHandle) -> None:
        rid = handle.replica_id
        if handle.restarts >= self.max_restarts:
            with self._lock:
                handle.state = FAILED
            logger.error("replica %d exhausted its %d restarts — fleet "
                         "stays degraded", rid, self.max_restarts)
            return
        with self._lock:
            handle.state = RESTARTING
            handle.restarts += 1
            handle.last_restart_at = time.monotonic()
        # Deterministic backoff (no jitter: drills must replay exactly).
        time.sleep(self.restart_backoff_s * handle.restarts)
        try:
            self._spawn(handle)
            self._await_ready(handle)
        except ReplicaStartupError as e:
            logger.error("replica %d restart failed: %s", rid, e)
            with self._lock:
                handle.state = DOWN
            # Next monitor pass will not see UP, so retry from here.
            self._restart(handle)
            return
        if self._on_recovered is not None:
            try:
                self._on_recovered(rid)
            except Exception:
                logger.exception("on_recovered(%d) callback failed", rid)

    # -- views / lifecycle ---------------------------------------------------

    def states(self) -> dict[int, str]:
        with self._lock:
            return {h.replica_id: h.state for h in self.replicas}

    def up_replicas(self) -> list[int]:
        with self._lock:
            return [h.replica_id for h in self.replicas if h.state == UP]

    def endpoint(self, replica_id: int) -> tuple[str, int]:
        h = self.replicas[replica_id]
        return h.host, h.port

    def stop(self) -> None:
        self._running = False
        if self._monitor is not None and self._monitor.is_alive():
            self._monitor.join(timeout=5.0)
        for handle in self.replicas:
            self.transport.terminate(handle, timeout_s=10.0)
            handle.state = DOWN

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
