"""photon-publish: versioned model-delta artifacts + their trust rules.

The fleet (serving/fleet.py) used to serve a frozen snapshot; a
production GLMix system refits per-entity random effects continuously
and publishes them WITHOUT downtime (ROADMAP item 1). This module owns
the at-rest half of that loop — the delta artifact format and the rules
for deciding one can be trusted; the in-memory half (row hot-swap) lives
in serving/model_store.py and the fleet-grade gating (canary → judge →
roll or roll back) in serving/fleet.py.

Artifact layout under one publish directory::

    delta-v000001/
        rows.npz      # per coordinate: "<cid>::ids" (k,) int64 vocabulary
                      # rows + "<cid>::rows" (k, d) float32 replacement
                      # coefficient rows (ABSOLUTE rows, not diffs — a
                      # re-applied delta is idempotent)
    delta-v000002/
        ...
        delta.json    # the COMMIT POINT, written LAST and atomically:
                      # version, parent version, per-file CRC32, row
                      # counts. A delta directory without a valid
                      # delta.json does not exist.

Crash/corruption discipline (the game/checkpoint.py contract, verbatim):
every file write is atomic (``utils/diskio.atomic_write``), the marker
carries the payload's CRC32 taken over the good bytes, and readers
verify before trusting. A SIGKILL mid-publish leaves a marker-less
directory — invisible; the previous version stays fully servable. Bit
rot (or the ``publish.delta_artifact`` corrupt fault) fails the CRC and
raises the defined :class:`DeltaCorrupt` instead of swapping garbage
rows into a live store.

Versions are MONOTONE: ``write`` always commits ``latest + 1`` and
stamps the parent, so a reader can tell a gap (missing/torn version)
from a clean chain and the fleet can refuse to apply out of order.

Failure taxonomy (docs/ROBUSTNESS.md publication ladder):

* :class:`DeltaCorrupt` — the artifact's bytes cannot be trusted
  (CRC mismatch, unparseable marker, missing payload);
* :class:`BadDelta`     — the artifact is intact but the CONTENT is
  unservable (non-finite rows, wrong dimension, ids outside the entity
  table) — what validation rejects before any store mutates;
* :class:`CanaryRejected` — the delta applied cleanly but the canary
  judge refused it (SLO burn, insane probe scores); raised by the
  fleet ladder after the rollback ran.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu.utils.diskio import atomic_write, file_crc32

logger = logging.getLogger("photon_ml_tpu.serving.publish")

_ROWS = "rows.npz"
_MARKER = "delta.json"
_DIR_RE = re.compile(r"^delta-v(\d{6,})$")
DELTA_FORMAT_VERSION = 1


class PublishError(RuntimeError):
    """Base class of the publication ladder's defined errors."""


class DeltaCorrupt(PublishError):
    """A delta artifact whose bytes fail their committed CRC (or whose
    marker is torn/unparseable) — never applied, by construction."""


class BadDelta(PublishError):
    """An intact delta whose CONTENT is unservable (NaN/Inf rows, wrong
    dimension, out-of-table ids) — rejected by validation before any
    store row mutates."""


class CanaryRejected(PublishError):
    """The canary judge refused a delta after its bake window; the
    canary (when it had applied) has already been rolled back and no
    non-canary replica ever saw the delta."""

    def __init__(self, version: int, reason: str):
        super().__init__(f"delta v{version} rejected at the canary: "
                         f"{reason}")
        self.version = version
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class ModelDelta:
    """One committed row-delta: coordinate id → (ids, replacement rows)."""

    version: int
    parent: int  # version this delta was cut against (0 = the base model)
    rows: dict[str, tuple[np.ndarray, np.ndarray]]
    path: str = ""

    @property
    def num_rows(self) -> int:
        return sum(int(ids.shape[0]) for ids, _ in self.rows.values())

    @property
    def coordinates(self) -> tuple[str, ...]:
        return tuple(sorted(self.rows))


def validate_delta(delta: ModelDelta,
                   dims: Optional[dict[str, tuple[int, int]]] = None
                   ) -> None:
    """Content validation — THE gate between an intact artifact and a
    live store. ``dims`` (coordinate → (num_entities, dim)) comes from
    the store about to apply; None checks only self-consistency.
    Raises :class:`BadDelta`; never mutates anything."""
    if not delta.rows:
        raise BadDelta(f"delta v{delta.version} carries no rows")
    for cid, (ids, rows) in delta.rows.items():
        if ids.ndim != 1 or rows.ndim != 2 \
                or ids.shape[0] != rows.shape[0]:
            raise BadDelta(
                f"delta v{delta.version} coordinate {cid!r}: ids "
                f"{ids.shape} and rows {rows.shape} do not pair up")
        if ids.shape[0] == 0:
            raise BadDelta(f"delta v{delta.version} coordinate {cid!r} "
                           f"is empty")
        if len(np.unique(ids)) != ids.shape[0]:
            raise BadDelta(f"delta v{delta.version} coordinate {cid!r} "
                           f"repeats entity ids (ambiguous row intent)")
        if not np.all(np.isfinite(rows)):
            raise BadDelta(
                f"delta v{delta.version} coordinate {cid!r} carries "
                f"non-finite coefficient rows — refusing to swap NaN/Inf "
                f"into a live store")
        if dims is not None:
            if cid not in dims:
                raise BadDelta(
                    f"delta v{delta.version} names coordinate {cid!r} "
                    f"the serving store does not hold "
                    f"(has {sorted(dims)})")
            num_entities, dim = dims[cid]
            if rows.shape[1] != dim:
                raise BadDelta(
                    f"delta v{delta.version} coordinate {cid!r}: rows "
                    f"are {rows.shape[1]}-dimensional, store expects "
                    f"{dim}")
            if ids.shape[0] and (int(ids.min()) < 0
                                 or int(ids.max()) >= num_entities):
                raise BadDelta(
                    f"delta v{delta.version} coordinate {cid!r}: entity "
                    f"ids outside [0, {num_entities})")


class DeltaStore:
    """Monotone-versioned delta artifacts under one publish directory.

    Thread-compatibility: one writer (the publisher process); readers
    (replicas applying a committed delta) only ever see committed
    generations — the marker is the commit point.
    """

    def __init__(self, directory: str):
        self.directory = directory

    # -- layout --------------------------------------------------------------

    def delta_dir(self, version: int) -> str:
        return os.path.join(self.directory, f"delta-v{version:06d}")

    def versions(self) -> list[int]:
        """Committed versions, ascending (marker present and parseable;
        payload CRC is verified at read time)."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = _DIR_RE.match(name)
            if not m:
                continue
            marker = os.path.join(self.directory, name, _MARKER)
            if os.path.exists(marker):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> int:
        versions = self.versions()
        return versions[-1] if versions else 0

    # -- write ---------------------------------------------------------------

    def write(self, rows: dict[str, tuple[np.ndarray, np.ndarray]],
              extra: Optional[dict] = None) -> ModelDelta:
        """Commit the next version. The payload is written first, its
        CRC32 taken over the good bytes, then the marker — so a kill
        anywhere before the marker leaves no committed version and a
        kill after leaves a fully committed one. ``publish.delta_write``
        is the crash seam; ``publish.delta_artifact`` the corruption
        seam (bit rot lands AFTER the checksum, the shape ``read`` must
        catch)."""
        parent = self.latest_version()
        version = parent + 1
        delta = ModelDelta(version=version, parent=parent, rows={
            cid: (np.asarray(ids, np.int64),
                  np.asarray(mat, np.float32))
            for cid, (ids, mat) in rows.items()})
        validate_delta(delta)
        d = self.delta_dir(version)
        os.makedirs(d, exist_ok=True)
        flt.fire(flt.sites.PUBLISH_DELTA_WRITE)
        payload = {}
        counts = {}
        for cid, (ids, mat) in delta.rows.items():
            payload[f"{cid}::ids"] = ids
            payload[f"{cid}::rows"] = mat
            counts[cid] = int(ids.shape[0])
        rows_path = os.path.join(d, _ROWS)
        atomic_write(rows_path, lambda f: np.savez(f, **payload))
        crc = file_crc32(rows_path)
        flt.corrupt_file(flt.sites.PUBLISH_DELTA_ARTIFACT, rows_path)
        # Occurrence 1 of the crash seam: payload on disk, marker not —
        # THE torn window a mid-publish SIGKILL must leave invisible.
        flt.fire(flt.sites.PUBLISH_DELTA_WRITE)
        marker = {
            "format": DELTA_FORMAT_VERSION,
            "version": version,
            "parent": parent,
            "crc": crc,
            "counts": counts,
        }
        if extra:
            marker["extra"] = extra
        body = json.dumps(marker, indent=2, sort_keys=True)
        atomic_write(os.path.join(d, _MARKER),
                     lambda f: f.write(body.encode()))
        logger.info("delta v%d committed: %d row(s) across %s -> %s",
                    version, delta.num_rows, delta.coordinates, d)
        return dataclasses.replace(delta, path=d)

    def retract(self, version: int) -> Optional[str]:
        """Take a rejected delta OUT of the version chain (the canary
        said no): the directory is renamed to ``rejected-v…`` — kept
        for forensics, invisible to ``versions()`` — so the next write
        reuses the number and the applied chain stays gapless. Returns
        the new path (None when the version does not exist)."""
        d = self.delta_dir(version)
        if not os.path.isdir(d):
            return None
        n = 0
        while True:
            target = os.path.join(self.directory,
                                  f"rejected-v{version:06d}.{n}")
            if not os.path.exists(target):
                break
            n += 1
        os.rename(d, target)
        logger.warning("delta v%d retracted -> %s", version, target)
        return target

    # -- read ----------------------------------------------------------------

    def read(self, version: int) -> ModelDelta:
        return read_delta(self.delta_dir(version))


def read_delta(path: str) -> ModelDelta:
    """Load one committed delta directory, verifying the marker and the
    payload CRC. Raises :class:`DeltaCorrupt` when the bytes cannot be
    trusted — the caller falls back to the previous committed version
    (which a torn write never touched)."""
    marker_path = os.path.join(path, _MARKER)
    if not os.path.exists(marker_path):
        raise DeltaCorrupt(f"{path} has no committed marker "
                           f"({_MARKER} missing — torn or absent publish)")
    try:
        with open(marker_path) as f:
            marker = json.load(f)
    except (OSError, ValueError) as e:
        raise DeltaCorrupt(f"{path} marker unreadable "
                           f"({type(e).__name__}: {e})")
    rows_path = os.path.join(path, _ROWS)
    try:
        got = file_crc32(rows_path)
    except OSError as e:
        raise DeltaCorrupt(f"{path} payload unreadable "
                           f"({type(e).__name__}: {e})")
    want = int(marker.get("crc", -1))
    if got != want:
        raise DeltaCorrupt(
            f"{path} payload fails its committed CRC (got {got}, marker "
            f"{want}) — refusing to apply corrupt rows")
    try:
        with np.load(rows_path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as e:
        raise DeltaCorrupt(f"{path} payload does not parse "
                           f"({type(e).__name__}: {e})")
    rows: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    for cid in marker.get("counts", {}):
        try:
            rows[cid] = (np.asarray(arrays[f"{cid}::ids"], np.int64),
                         np.asarray(arrays[f"{cid}::rows"], np.float32))
        except KeyError:
            raise DeltaCorrupt(f"{path} marker names coordinate {cid!r} "
                               f"the payload does not carry")
    return ModelDelta(version=int(marker["version"]),
                      parent=int(marker.get("parent", 0)),
                      rows=rows, path=path)


def fetch_delta(url: str, dest_root: str, timeout_s: float = 30.0) -> str:
    """Pull one delta directory's artifacts over HTTP (the wire leg of
    docs/SERVING.md "Multi-host fleet": a DeltaArtifactServer exports
    the publisher's directory; remote replicas call this instead of
    assuming a shared filesystem). Returns the LOCAL delta directory,
    ready for :func:`read_delta`.

    The at-rest commit discipline crosses the wire intact: the payload
    is fetched and atomically written FIRST, the marker LAST — a torn
    fetch (connection cut, ``fabric.delta_fetch`` injection) leaves a
    marker-less local directory that :func:`read_delta` refuses, and
    the previously applied version stays servable. Every transfer
    failure lands in the same :class:`DeltaCorrupt` taxonomy as a torn
    shared-filesystem write; CRC verification happens in
    :func:`read_delta` exactly as for a local artifact.
    """
    import urllib.request

    from photon_ml_tpu import obs

    url = url.rstrip("/")
    name = url.rsplit("/", 1)[-1]
    if not _DIR_RE.match(name):
        raise DeltaCorrupt(f"{url} does not name a delta directory "
                           f"(want .../delta-vNNNNNN)")
    dest = os.path.join(dest_root, name)
    os.makedirs(dest, exist_ok=True)
    total = 0
    # Payload first, marker LAST — the marker IS the commit point.
    for i, fname in enumerate((_ROWS, _MARKER)):
        try:
            flt.fire(flt.sites.FABRIC_DELTA_FETCH, index=i)
            with urllib.request.urlopen(f"{url}/{fname}",
                                        timeout=timeout_s) as resp:
                blob = resp.read()
        except (OSError, ValueError) as e:
            raise DeltaCorrupt(
                f"fetch of {url}/{fname} failed ({type(e).__name__}: "
                f"{e}) — previous version stays servable")
        atomic_write(os.path.join(dest, fname),
                     lambda f, b=blob: f.write(b))
        total += len(blob)
    mx = obs.metrics()
    if mx is not None:
        mx.counter("photon_fabric_delta_fetch_total").inc()
        mx.counter("photon_fabric_delta_fetch_bytes_total").inc(total)
    logger.info("fetched delta %s from %s (%d bytes)", name, url, total)
    return dest
