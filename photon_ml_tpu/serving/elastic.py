"""photon-elastic: the overload control loop for the replicated fleet.

ROADMAP item 2's closing move: at Zipf skew the static ``id %
num_shards`` map concentrates the head on one replica — the fleet's
knee QPS becomes its hottest shard's knee, not its capacity. Every
signal needed to fix that is already measured (per-shard request
counts, queue depth, error-budget burn, stage seconds); this module
closes the loop from measurement to ACTION, on the supervisor's
monitor cadence (the Snap ML hierarchical resource-matching idea
applied to serving — PAPERS.md):

- **Heat model** (``serving/metrics.ShardHeat``): per-shard sliding
  window of requests, distinct entities, and observed service seconds,
  published as ``photon_fleet_shard_heat{shard=}`` and read here each
  tick.
- **Split + migrate** (``ShardMap.split``/``migrate``): a shard
  carrying more than ``split_factor`` × the mean heat — and more than
  one entity, one user cannot be split — splits into consistent-hash
  children (cold entities never remap) and one child migrates to the
  coldest live replica, with the re-home discipline: the target is
  probed healthy BEFORE the table swap, the swap is one version bump
  under the map lock, and in-flight requests drain through the retry
  path that re-resolves owners. Scores are bit-identical throughout —
  every replica holds the full host store.
- **Burn-driven autoscale** (``ReplicaSupervisor.add_replica`` /
  ``retire``): error-budget burn, fleet queue depth, or irreducible
  heat imbalance sustained over ``hysteresis_ticks`` scales UP (spawn
  → warm via the replica args' ``--boot-warmup`` → admit to the map →
  replay the committed delta chain → migrate the hottest shards onto
  it); sustained idle scales DOWN (drain → migrate every shard away,
  each leg target-probed → retire), and a replica is NEVER retired
  while it owns a shard — the guard is structural
  (``ShardMap.remove_replica`` refuses).
- **Adaptive hedging**: ``hedge_after_s`` re-derives from the p99 of
  the router's recent successful sends (× ``hedge_factor``, clamped)
  instead of a static knob — the hedge threshold tracks what "slow"
  currently means.
- **Brownout ladder**: when burn crosses ``brownout_burn`` AND one
  shard carries ``brownout_heat_frac`` of the window's heat, admission
  tightens for THAT shard first (its 503s name it) before the
  fleet-wide bound engages; ``FleetDegraded`` events mark both edges.

Every decision writes an ``elastic`` ledger row carrying its
triggering evidence (heat snapshot, burn rate, queue fraction, map
version) — ``photon-obs tail --elastic`` renders the decision tape.
Fault sites ``fleet.split`` / ``fleet.migrate`` / ``fleet.scale`` fire
BEFORE each mutation, so a chaos fault leaves the map at exactly the
old version; the mutations themselves are single version bumps under
the map lock, so the map is never torn (docs/ROBUSTNESS.md).

All decisions are pure functions of the sampled window — two
controllers reading the same tape act identically, so drills replay.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Optional

from photon_ml_tpu import faults as flt
from photon_ml_tpu.serving.router import route_key
from photon_ml_tpu.serving.supervisor import _probe_healthz
from photon_ml_tpu.utils.events import (FleetDegraded, ReplicaScaled,
                                        ShardSplit)

logger = logging.getLogger("photon_ml_tpu.serving.fleet")

__all__ = ["ElasticConfig", "ElasticController", "parse_elastic_config"]


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Knobs of the elastic control loop (docs/SERVING.md "Elastic
    fleet" documents each threshold's semantics)."""

    interval_s: float = 0.5        # control-loop cadence
    heat_window_s: float = 30.0    # sliding heat window
    # -- split/migrate ------------------------------------------------------
    split_factor: float = 4.0      # hottest > factor × mean heat → split
    min_heat_requests: int = 32    # below this the window is noise
    max_shards: int = 64           # leaf-count cap (split budget)
    # -- autoscale ----------------------------------------------------------
    scale_up_burn: float = 1.0     # error-budget burn rate threshold
    scale_up_queue_frac: float = 0.5   # fleet inflight / max_inflight
    scale_up_heat_frac: float = 0.7    # one replica carries > this share
    scale_down_idle_frac: float = 0.05  # inflight share marking idle
    scale_down_idle_qps: float = 0.5   # window QPS below this is idle
    hysteresis_ticks: int = 3      # consecutive ticks before acting
    cooldown_s: float = 10.0       # between scale actions
    min_replicas: int = 1
    max_replicas: int = 8
    # -- adaptive hedging ---------------------------------------------------
    hedge_auto: bool = True
    hedge_factor: float = 1.5      # hedge_after = factor × observed p99
    hedge_min_s: float = 0.010
    hedge_max_s: float = 5.0
    # -- brownout -----------------------------------------------------------
    brownout_burn: float = 2.0     # burn rate engaging per-shard admission
    brownout_heat_frac: float = 0.5  # the shard share that names the culprit


def parse_elastic_config(spec: str) -> ElasticConfig:
    """Parse the ``key=value,...`` mini-DSL of ``photon-game-fleet
    --elastic`` (the ``--staging``/``--streaming`` idiom). An empty
    spec takes every default.

    Keys: interval, window, split_factor, min_heat, max_shards, burn,
    queue_frac, heat_frac, idle_frac, hysteresis, cooldown,
    min_replicas, max_replicas, hedge (on|off), hedge_factor,
    brownout_burn, brownout_frac.
    """
    fields = {
        "interval": ("interval_s", float),
        "window": ("heat_window_s", float),
        "split_factor": ("split_factor", float),
        "min_heat": ("min_heat_requests", int),
        "max_shards": ("max_shards", int),
        "burn": ("scale_up_burn", float),
        "queue_frac": ("scale_up_queue_frac", float),
        "heat_frac": ("scale_up_heat_frac", float),
        "idle_frac": ("scale_down_idle_frac", float),
        "idle_qps": ("scale_down_idle_qps", float),
        "hysteresis": ("hysteresis_ticks", int),
        "cooldown": ("cooldown_s", float),
        "min_replicas": ("min_replicas", int),
        "max_replicas": ("max_replicas", int),
        "hedge": ("hedge_auto", lambda v: v.lower() in ("1", "on",
                                                        "true", "yes")),
        "hedge_factor": ("hedge_factor", float),
        "brownout_burn": ("brownout_burn", float),
        "brownout_frac": ("brownout_heat_frac", float),
    }
    kwargs = {}
    for part in (p.strip() for p in spec.split(",") if p.strip()):
        if "=" not in part:
            raise ValueError(f"elastic spec entry {part!r} is not "
                             f"key=value")
        key, value = part.split("=", 1)
        if key.strip() not in fields:
            raise ValueError(f"unknown elastic key {key.strip()!r}; "
                             f"expected {sorted(fields)}")
        name, conv = fields[key.strip()]
        kwargs[name] = conv(value.strip())
    return ElasticConfig(**kwargs)


class ElasticController:
    """The control loop. One instance per :class:`ServingFleet`;
    ``start()`` runs ``tick()`` on a daemon thread every
    ``interval_s``, or tests call ``tick()`` directly — every decision
    is a pure function of the sampled window, so direct ticks and the
    thread behave identically."""

    def __init__(self, fleet, config: Optional[ElasticConfig] = None):
        self.fleet = fleet
        self.config = config or ElasticConfig()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Hysteresis counters (controller-thread-private: tick() is
        # never concurrent with itself).
        self._hot_ticks = 0
        self._idle_ticks = 0
        self._last_scale_at = 0.0
        self._brownout_on = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="photon-fleet-elastic", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:
                # The control loop must outlive any one bad decision;
                # the failed action already logged its own evidence.
                logger.exception("elastic tick failed — the next tick "
                                 "re-samples from scratch")

    # -- signal sampling -----------------------------------------------------

    def sample(self) -> dict:
        """One coherent reading of every control signal. Heat events
        re-resolve through the CURRENT map, so a split's evidence
        follows the children instead of re-indicting the parent."""
        fleet = self.fleet
        heat = fleet.heat.snapshot(
            resolver=lambda key: fleet.shard_map.shard_of_key(
                route_key(key)))
        total = sum(r["heat"] for r in heat.values())
        slo = fleet.metrics.slo.snapshot()
        by_replica: dict[int, float] = {}
        for shard, row in heat.items():
            try:
                owner = fleet.shard_map.owner(shard)
            except KeyError:
                continue  # shard split away between snapshot and now
            by_replica[owner] = by_replica.get(owner, 0.0) + row["heat"]
        window_reqs = sum(r["requests"] for r in heat.values())
        return {
            "heat": heat,
            "total_heat": total,
            "heat_by_replica": by_replica,
            "burn_rate": float(slo.get("budget_burn_rate", 0.0)),
            "requests_in_window": int(slo.get("requests_in_window", 0)),
            "window_qps": window_reqs / max(fleet.heat.window_s, 1e-9),
            "inflight_frac": (fleet.inflight
                              / max(fleet.max_inflight, 1)),
            "map_version": fleet.shard_map.version,
            "live_replicas": fleet.shard_map.live(),
        }

    # -- one control cycle ---------------------------------------------------

    def tick(self) -> dict:
        """One decision pass; returns the actions taken (tests assert
        on this, the thread discards it)."""
        s = self.sample()
        actions: dict = {}
        self._tune_hedging(actions)
        self._update_brownout(s, actions)
        # Split first — the cheaper action: a hot shard that CAN be
        # subdivided should spread over the existing replicas before
        # any new hardware spawns; pressure that splitting cannot
        # relieve (one hot entity, or every replica already hot)
        # persists into the next ticks and scales.
        if not self._maybe_split(s, actions):
            self._maybe_scale_up(s, actions)
        self._maybe_scale_down(s, actions)
        return actions

    # -- adaptive hedging ----------------------------------------------------

    def _tune_hedging(self, actions: dict) -> None:
        cfg = self.config
        if not cfg.hedge_auto:
            return
        p99 = self.fleet.router.observed_send_p99()
        if p99 is None:
            return
        target = min(max(cfg.hedge_factor * p99, cfg.hedge_min_s),
                     cfg.hedge_max_s)
        current = self.fleet.router.hedge_after_s
        # Re-tune only on material movement — a ledger row per tick
        # would be noise, and sub-ms thrash has no routing effect.
        if current is not None and abs(target - current) \
                <= 0.2 * current:
            return
        self.fleet.router.hedge_after_s = target
        actions["hedge_after_s"] = target
        self.fleet._elastic_record(
            action="hedge_tune", hedge_after_s=round(target, 6),
            observed_send_p99_s=round(p99, 6))
        logger.info("hedge_after_s auto-tuned to %.3fs (observed send "
                    "p99 %.3fs × %.2f)", target, p99, cfg.hedge_factor)

    # -- brownout ladder -----------------------------------------------------

    def _update_brownout(self, s: dict, actions: dict) -> None:
        cfg = self.config
        total = s["total_heat"]
        hot = []
        if total > 0 and s["requests_in_window"] >= cfg.min_heat_requests:
            hot = [shard for shard, row in s["heat"].items()
                   if row["heat"] / total >= cfg.brownout_heat_frac]
        engage = bool(hot) and s["burn_rate"] >= cfg.brownout_burn
        if engage and not self._brownout_on:
            reason = (f"burn {s['burn_rate']:.2f} >= "
                      f"{cfg.brownout_burn:.2f} with shard(s) {hot} "
                      f"over {cfg.brownout_heat_frac:.0%} of window "
                      f"heat")
            self.fleet.set_brownout(hot, reason)
            self._brownout_on = True
            actions["brownout"] = hot
        elif self._brownout_on and (not hot or s["burn_rate"]
                                    <= 0.5 * cfg.brownout_burn):
            # Release with hysteresis: half the engage threshold, so
            # the ladder does not flap at the boundary.
            self.fleet.set_brownout([], "burn back under half the "
                                        "brownout threshold")
            self._brownout_on = False
            actions["brownout_clear"] = True

    # -- split + migrate -----------------------------------------------------

    def _maybe_split(self, s: dict, actions: dict) -> bool:
        cfg = self.config
        heat = s["heat"]
        if s["requests_in_window"] < cfg.min_heat_requests:
            return False
        leaves = self.fleet.shard_map.shards()
        if len(leaves) >= cfg.max_shards:
            return False
        if not heat or s["total_heat"] <= 0:
            return False
        mean = s["total_heat"] / max(len(leaves), 1)
        # Hottest SPLITTABLE shard: more than one distinct entity in
        # the window (a single hot user cannot be split apart) and
        # over the factor.
        candidates = sorted(
            ((row["heat"], shard) for shard, row in heat.items()
             if row["entities"] > 1 and shard in
             set(leaves)),
            reverse=True)
        if not candidates:
            return False
        top_heat, shard = candidates[0]
        if top_heat < cfg.split_factor * mean:
            return False
        heat_frac = top_heat / s["total_heat"]
        try:
            flt.fire(flt.sites.FLEET_SPLIT, index=shard)
        except Exception as e:
            logger.error("fleet.split fault on shard %d (%s) — map "
                         "stays at version %d", shard, e,
                         self.fleet.shard_map.version)
            return False
        a, b = self.fleet.shard_map.split(shard)
        self.fleet.metrics.record_split()
        self.fleet.emitter.emit(ShardSplit(
            shard=shard, children=(a, b), heat_fraction=heat_frac,
            map_version=self.fleet.shard_map.version))
        self.fleet._elastic_record(
            action="split", shard=shard, children=[a, b],
            heat_fraction=round(heat_frac, 4),
            heat=round(top_heat, 3), mean_heat=round(mean, 3),
            map_version=self.fleet.shard_map.version)
        logger.info("split hot shard %d (%.0f%% of window heat) into "
                    "%d + %d (map v%d)", shard, 100 * heat_frac, a, b,
                    self.fleet.shard_map.version)
        actions["split"] = (shard, a, b)
        # Move one child to the coldest live replica so the split
        # actually spreads load (both children inherit the owner).
        target = self._coldest_replica(
            s, exclude={self.fleet.shard_map.owner(b)})
        if target is not None:
            if self._migrate(b, target, reason="post-split spread"):
                actions["migrate"] = (b, target)
        return True

    def _coldest_replica(self, s: dict,
                         exclude: set[int] = frozenset()) -> \
            Optional[int]:
        live = [r for r in s["live_replicas"] if r not in exclude]
        if not live:
            return None
        by_replica = s["heat_by_replica"]
        return min(live, key=lambda r: (by_replica.get(r, 0.0), r))

    def _migrate(self, shard: int, target: int, reason: str) -> bool:
        """One migration leg under the re-home discipline: probe the
        target healthy FIRST, then swap the table (one version bump).
        In-flight requests to the old owner finish there — it serves
        the same bits from its own host store; new requests route to
        the target."""
        fleet = self.fleet
        try:
            flt.fire(flt.sites.FLEET_MIGRATE, index=shard)
            host, port = fleet.supervisor.endpoint(target)
            _probe_healthz(f"http://{host}:{port}",
                           fleet.probe_timeout_s)
            old = fleet.shard_map.migrate(shard, target)
        except Exception as e:
            # A failed leg changes NOTHING: the probe precedes the
            # swap, and the swap is atomic — the map stays at the old
            # version with a valid owner.
            logger.error("migration of shard %d → replica %d aborted "
                         "(%s: %s) — map stays at version %d", shard,
                         target, type(e).__name__, e,
                         fleet.shard_map.version)
            return False
        fleet.metrics.record_migration()
        fleet._elastic_record(
            action="migrate", shard=shard, source=old, target=target,
            reason=reason, map_version=fleet.shard_map.version)
        logger.info("migrated shard %d: replica %d → %d (%s, map v%d)",
                    shard, old, target, reason,
                    fleet.shard_map.version)
        return True

    # -- autoscale -----------------------------------------------------------

    def _pressure(self, s: dict) -> Optional[str]:
        """The scale-up signal, or None. Named so the ledger row and
        the ReplicaScaled event carry WHY."""
        cfg = self.config
        if s["burn_rate"] >= cfg.scale_up_burn \
                and s["requests_in_window"] >= cfg.min_heat_requests:
            return (f"error-budget burn {s['burn_rate']:.2f} >= "
                    f"{cfg.scale_up_burn:.2f}")
        if s["inflight_frac"] >= cfg.scale_up_queue_frac:
            return (f"fleet queue {s['inflight_frac']:.0%} >= "
                    f"{cfg.scale_up_queue_frac:.0%} of max_inflight")
        by_replica = s["heat_by_replica"]
        if s["total_heat"] > 0 and by_replica \
                and s["requests_in_window"] >= cfg.min_heat_requests:
            top = max(by_replica.values())
            if top / s["total_heat"] >= cfg.scale_up_heat_frac \
                    and len(s["live_replicas"]) >= 1:
                return (f"one replica carries "
                        f"{top / s['total_heat']:.0%} of window heat "
                        f">= {cfg.scale_up_heat_frac:.0%}")
        return None

    def _maybe_scale_up(self, s: dict, actions: dict) -> bool:
        cfg = self.config
        reason = self._pressure(s)
        if reason is None:
            self._hot_ticks = 0
            return False
        self._hot_ticks += 1
        if self._hot_ticks < cfg.hysteresis_ticks:
            return False
        now = time.monotonic()
        if now - self._last_scale_at < cfg.cooldown_s:
            return False
        if len(s["live_replicas"]) >= cfg.max_replicas:
            return False
        try:
            flt.fire(flt.sites.FLEET_SCALE, index=len(
                s["live_replicas"]))
        except Exception as e:
            logger.error("fleet.scale fault (%s) — no replica "
                         "spawned, map unchanged", e)
            return False
        try:
            rid = self.fleet.add_replica()
        except Exception as e:
            logger.error("scale-up failed (%s: %s) — the fleet keeps "
                         "its current shape", type(e).__name__, e)
            return False
        self._hot_ticks = 0
        self._last_scale_at = now
        n = len(self.fleet.shard_map.live())
        self.fleet.metrics.record_scale("up")
        self.fleet.emitter.emit(ReplicaScaled(
            direction="up", replica_id=rid, num_replicas=n,
            reason=reason))
        self.fleet._elastic_record(
            action="scale_up", replica=rid, num_replicas=n,
            reason=reason, burn_rate=round(s["burn_rate"], 4),
            inflight_frac=round(s["inflight_frac"], 4),
            map_version=self.fleet.shard_map.version)
        logger.info("scaled UP to %d replicas (replica %d admitted): "
                    "%s", n, rid, reason)
        actions["scale_up"] = rid
        # Move the hottest shards onto the newcomer until it carries a
        # fair share — the admit-then-rebalance leg.
        heat_sorted = sorted(
            ((row["heat"], shard) for shard, row in s["heat"].items()),
            reverse=True)
        fair = max(1, len(self.fleet.shard_map.shards()) // max(n, 1))
        moved = 0
        for _, shard in heat_sorted:
            if moved >= fair:
                break
            try:
                if self.fleet.shard_map.owner(shard) == rid:
                    continue
            except KeyError:
                continue
            if self._migrate(shard, rid, reason="scale-up rebalance"):
                moved += 1
        return True

    def _maybe_scale_down(self, s: dict, actions: dict) -> None:
        cfg = self.config
        if actions.keys() & {"split", "scale_up", "migrate",
                             "brownout"}:
            # A tick that just acted on pressure is not an idle tick.
            self._idle_ticks = 0
            return
        busy = (s["burn_rate"] > 0.0
                or s["inflight_frac"] > cfg.scale_down_idle_frac
                or s["window_qps"] > cfg.scale_down_idle_qps
                or self._brownout_on)
        if busy:
            self._idle_ticks = 0
            return
        self._idle_ticks += 1
        if self._idle_ticks < cfg.hysteresis_ticks:
            return
        live = s["live_replicas"]
        if len(live) <= cfg.min_replicas:
            return
        now = time.monotonic()
        if now - self._last_scale_at < cfg.cooldown_s:
            return
        victim = self._coldest_replica(s)
        if victim is None:
            return
        try:
            flt.fire(flt.sites.FLEET_SCALE, index=victim)
        except Exception as e:
            logger.error("fleet.scale fault on scale-down (%s) — "
                         "replica %d keeps serving", e, victim)
            return
        fleet = self.fleet
        fleet.shard_map.set_draining(victim, True)
        owned = fleet.shard_map.shards_of(victim)
        for shard in owned:
            target = self._coldest_replica(s, exclude={victim})
            if target is None or not self._migrate(
                    shard, target, reason="scale-down drain"):
                # Could not place a shard: undo the drain — the victim
                # stays a full owner; NEVER retire the last owner.
                fleet.shard_map.set_draining(victim, False)
                logger.warning(
                    "scale-down of replica %d aborted: shard %d has "
                    "no healthy destination", victim, shard)
                return
        try:
            fleet.shard_map.remove_replica(victim)
        except ValueError as e:
            fleet.shard_map.set_draining(victim, False)
            logger.error("scale-down refused: %s", e)
            return
        fleet.supervisor.retire(victim)
        self._idle_ticks = 0
        self._last_scale_at = now
        n = len(fleet.shard_map.live())
        fleet.metrics.record_scale("down")
        fleet.emitter.emit(ReplicaScaled(
            direction="down", replica_id=victim, num_replicas=n,
            reason="sustained idle"))
        fleet._elastic_record(
            action="scale_down", replica=victim, num_replicas=n,
            reason="sustained idle",
            inflight_frac=round(s["inflight_frac"], 4),
            map_version=fleet.shard_map.version)
        logger.info("scaled DOWN to %d replicas (replica %d drained + "
                    "retired)", n, victim)
        actions["scale_down"] = victim
