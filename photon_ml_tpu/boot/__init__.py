"""photon-boot: mmap model artifacts + atomic generation swap.

ROADMAP item 5's serving half: a restarted replica used to parse the
full npz host store before taking traffic — the measured floor under
``fleet_rehome_seconds``. This package publishes GameModels in the
columnar mmap format the ingest cache already proves
(``ingest/cache.py`` v3 CRC discipline), so boot becomes an ``mmap()``
instead of a parse:

* ``boot/mapfmt.py`` — one 64-byte-aligned columnar blob per
  coordinate + per-blob CRC32 ``.ok`` markers + a directory-level
  commit marker written LAST (``utils/diskio`` discipline); loads are
  zero-copy views over the page cache, bit-identical to the npz path.
* ``boot/generations.py`` — monotone ``gen-%06d`` directories with a
  two-generation retention, an atomic ``current`` symlink swap, a
  corruption fallback ladder (``BootRecovered``), and a compaction
  path folding a committed ``DeltaStore`` chain (serving/publish.py)
  into the next generation.

Import cost: numpy + stdlib only at the package level (JAX enters only
through the model classes a load constructs), so the CLI layers stay
fast. See docs/SERVING.md "Sub-second restart".
"""

from __future__ import annotations

# mapfmt first: generations imports it back through the package.
from photon_ml_tpu.boot.mapfmt import (MapCorrupt, MapFormatError,
                                       is_mapped_array, is_mapped_model,
                                       load_mapped_model,
                                       write_mapped_model)
from photon_ml_tpu.boot.generations import (GenerationError,
                                            GenerationStore)

__all__ = [
    "GenerationError", "GenerationStore", "MapCorrupt", "MapFormatError",
    "is_mapped_array", "is_mapped_model", "load_mapped_model",
    "resolve_model_path", "write_mapped_model",
]


def resolve_model_path(path: str):
    """Classify a ``--model-dir`` argument for the boot path: returns
    ``(kind, resolved_path, meta)`` where ``kind`` is one of

    * ``"generations"`` — a :class:`GenerationStore` root (``gen-*``
      dirs / ``current`` pointer): boot the CURRENT generation with the
      fallback ladder; ``meta`` carries generation + model_version;
    * ``"mapped"``     — a single committed mapped-model directory;
    * ``"npz"``        — anything else (the classic
      ``models/io.load_game_model`` layout, Avro included).

    Detection is by layout, not by flag, so every serving entry point
    (``photon-game-serve``, the fleet's replicas, benches) boots from a
    generation root with zero new plumbing.
    """
    import os

    if GenerationStore.looks_like(path):
        return "generations", path, None
    if is_mapped_model(path):
        return "mapped", path, None
    return "npz", os.path.normpath(path), None
