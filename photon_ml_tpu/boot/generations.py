"""Generation store: atomic model publication for sub-second restart.

Layout under one generation root::

    gen-000001/        # a committed mapped model (boot/mapfmt.py)
    gen-000002/
    current -> gen-000002   # the serving pointer, swapped ATOMICALLY

Publication writes the next ``gen-%06d`` directory (the mapfmt marker
is its commit point — a kill mid-write leaves an invisible directory),
then swaps ``current`` via a temp symlink + ``os.replace``: readers see
the old generation or the new one, never a mix. Rollback is a re-point.
Retention keeps the newest two COMMITTED generations (the
game/checkpoint.py two-generation discipline at the model tier), and
the pointed-at generation is never pruned.

Boot ladder (docs/ROBUSTNESS.md): ``load_current`` verifies the current
generation's blob CRCs; corruption falls back ONE committed generation
with a loud :class:`~photon_ml_tpu.utils.events.BootRecovered` event +
``photon_boot_recoveries_total``; both generations bad raises the
defined :class:`GenerationError` — recovery degrades, it never boots
silently wrong rows.

Compaction folds a committed ``DeltaStore`` chain (serving/publish.py)
into the NEXT generation: a replica booting the compacted generation
starts at the folded ``model_version``, so the fleet's restart replay
has nothing to re-apply — publication cost amortizes into the artifact
instead of replaying forever. ``compact`` is bit-exact: folding deltas
v..k into the tables equals replaying v..k onto a booted store (the
tested contract).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import shutil
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu.boot import mapfmt
from photon_ml_tpu.utils import events as ev_mod
from photon_ml_tpu.utils.diskio import atomic_write

logger = logging.getLogger("photon_ml_tpu.boot")

_GEN_RE = re.compile(r"^gen-(\d{6,})$")
_CURRENT = "current"


class GenerationError(RuntimeError):
    """No committed generation can be trusted (or a compaction chain is
    broken) — the defined end of the boot ladder."""


class GenerationStore:
    """Monotone ``gen-%06d`` mapped-model generations under one root.

    Thread-compatibility mirrors serving/publish.DeltaStore: one writer
    (the publisher), many readers (booting replicas) that only ever see
    committed generations.
    """

    def __init__(self, root: str, retain: int = 2):
        if retain < 2:
            raise ValueError(f"retain must keep >= 2 generations "
                             f"(rollback needs one to fall back to), "
                             f"got {retain}")
        self.root = root
        self.retain = int(retain)

    @staticmethod
    def looks_like(path: str) -> bool:
        """Layout probe for the boot path's auto-detection: a
        ``current`` pointer or any ``gen-*`` directory."""
        if not os.path.isdir(path):
            return False
        if os.path.lexists(os.path.join(path, _CURRENT)):
            return True
        return any(_GEN_RE.match(n) for n in os.listdir(path))

    # -- layout --------------------------------------------------------------

    def gen_dir(self, version: int) -> str:
        return os.path.join(self.root, f"gen-{version:06d}")

    def versions(self) -> list[int]:
        """Committed generations, ascending (mapfmt marker present;
        blob CRCs are verified at load time)."""
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            m = _GEN_RE.match(name)
            if m and mapfmt.is_mapped_model(os.path.join(self.root, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> int:
        versions = self.versions()
        return versions[-1] if versions else 0

    def current_version(self) -> int:
        """The generation ``current`` points at; a missing/dangling
        pointer degrades to the newest committed generation (a crash
        between marker and swap must not strand a bootable root)."""
        link = os.path.join(self.root, _CURRENT)
        try:
            target = os.path.basename(os.readlink(link))
            m = _GEN_RE.match(target)
            if m and int(m.group(1)) in set(self.versions()):
                return int(m.group(1))
        except OSError:
            pass
        return self.latest_version()

    def current_path(self) -> str:
        v = self.current_version()
        if v == 0:
            raise GenerationError(
                f"{self.root} holds no committed generation")
        return self.gen_dir(v)

    # -- write ---------------------------------------------------------------

    def _swap(self, version: int) -> None:
        """Re-point ``current`` atomically (temp symlink +
        ``os.replace`` — the mapfmt/diskio rename discipline applied to
        the pointer itself)."""
        link = os.path.join(self.root, _CURRENT)
        tmp = link + ".tmp"
        try:
            os.unlink(tmp)
        except OSError:
            pass
        os.symlink(f"gen-{version:06d}", tmp)
        os.replace(tmp, link)

    def _prune(self) -> None:
        """Drop generations older than the newest ``retain`` committed
        ones; the pointed-at generation always survives."""
        versions = self.versions()
        keep = set(versions[-self.retain:])
        keep.add(self.current_version())
        for v in versions:
            if v not in keep:
                shutil.rmtree(self.gen_dir(v), ignore_errors=True)
                logger.info("generation gen-%06d pruned (retention %d)",
                            v, self.retain)

    def publish(self, model, model_version: int = 0,
                extra: Optional[dict] = None) -> tuple[int, str]:
        """Commit ``model`` as the next generation and swap ``current``
        to it. ``model_version`` records the newest publication delta
        (serving/publish.py chain) already FOLDED into these tables
        (0 = the base offline fit) — a booted replica starts its delta
        chain there. Returns ``(generation, path)``."""
        version = self.latest_version() + 1
        d = self.gen_dir(version)
        meta = {"generation": version,
                "model_version": int(model_version)}
        if extra:
            meta.update(extra)
        mapfmt.write_mapped_model(model, d, extra=meta)
        self._swap(version)
        self._prune()
        logger.info("generation gen-%06d live (model_version %d) -> %s",
                    version, model_version, d)
        return version, d

    def rollback(self) -> int:
        """Re-point ``current`` one committed generation back (the
        publication ladder's model-tier undo). Returns the now-current
        generation."""
        versions = self.versions()
        cur = self.current_version()
        older = [v for v in versions if v < cur]
        if not older:
            raise GenerationError(
                f"{self.root} has no generation older than gen-{cur:06d} "
                f"to roll back to")
        self._swap(older[-1])
        logger.warning("generation store rolled back: gen-%06d -> "
                       "gen-%06d", cur, older[-1])
        return older[-1]

    # -- read (the boot ladder) ----------------------------------------------

    def load_current(self, verify: bool = True):
        """Boot the current generation; on corruption fall back ONE
        committed generation with a loud ``BootRecovered`` event.

        Returns ``(GameModel, marker, generation)``. Raises
        :class:`GenerationError` when no generation can be trusted.
        """
        versions = self.versions()
        if not versions:
            raise GenerationError(
                f"{self.root} holds no committed generation")
        cur = self.current_version()
        candidates = [cur] + [v for v in reversed(versions) if v < cur][:1]
        reason = ""
        for i, v in enumerate(candidates):
            try:
                model, marker = mapfmt.load_mapped_model(
                    self.gen_dir(v), verify=verify)
            except mapfmt.MapFormatError as e:
                if not reason:
                    reason = f"{type(e).__name__}: {e}"
                logger.warning("generation gen-%06d failed verification "
                               "(%s)", v, e)
                continue
            if i > 0:
                logger.error(
                    "current generation gen-%06d is corrupt (%s) — "
                    "BOOTING the previous committed generation "
                    "gen-%06d; its rows may be stale until the next "
                    "publish", cur, reason, v)
                ev_mod.default_emitter.emit(ev_mod.BootRecovered(
                    directory=self.root, from_version=cur, to_version=v,
                    reason=reason))
                from photon_ml_tpu import obs

                mx = obs.metrics()
                if mx is not None:
                    mx.counter("photon_boot_recoveries_total").inc()
            return model, marker, v
        raise GenerationError(
            f"{self.root}: no trustworthy generation "
            f"({reason or 'nothing committed'}) — refusing to boot "
            f"silently wrong rows")

    # -- compaction (the DeltaStore fold) ------------------------------------

    def compact(self, delta_store) -> Optional[tuple[int, str]]:
        """Fold every committed delta NEWER than the current
        generation's ``model_version`` into the next generation;
        returns ``(generation, path)``, or None when the chain is
        already fully folded (idempotent re-runs).

        Bit-exact by construction: a delta's rows are ABSOLUTE
        replacement rows (serving/publish.py), so folding them into the
        dense tables in chain order equals replaying the chain onto a
        booted store. The chain must be gapless from the generation's
        folded version; a gap raises :class:`GenerationError` (a
        compacted artifact that silently skipped a delta would serve
        wrong rows forever).

        Crash seam: ``boot.compact`` fires before any bytes move — a
        kill mid-compaction leaves a marker-less generation directory
        (invisible) and the previous generation fully servable.
        """
        from photon_ml_tpu.game.models import RandomEffectModel

        model, marker, gen = self.load_current()
        base_version = int(marker.get("model_version", 0))
        versions = [v for v in delta_store.versions() if v > base_version]
        if not versions:
            # Already fully folded — a re-run of the publisher must be
            # idempotent, so this is a no-op, not a failure.
            logger.info("nothing to compact: no committed delta newer "
                        "than model_version %d (gen-%06d)", base_version,
                        gen)
            return None
        expect = list(range(base_version + 1, versions[-1] + 1))
        if versions != expect:
            raise GenerationError(
                f"delta chain has gaps past model_version "
                f"{base_version}: found {versions}, need {expect} — "
                f"refusing to fold an incomplete chain")
        flt.fire(flt.sites.BOOT_COMPACT)
        tables: dict[str, np.ndarray] = {}
        folded_rows = 0
        for v in versions:
            delta = delta_store.read(v)
            for cid, (ids, rows) in delta.rows.items():
                m = model.models.get(cid)
                if not isinstance(m, RandomEffectModel):
                    raise GenerationError(
                        f"delta v{v} targets coordinate {cid!r} which "
                        f"is not a dense random effect — compaction "
                        f"serves the same representations row hot-swap "
                        f"does")
                t = tables.get(cid)
                if t is None:
                    # ONE writable copy per touched coordinate for the
                    # whole fold (untouched coordinates stay mapped).
                    t = np.array(np.asarray(m.means, np.float32))
                    tables[cid] = t
                t[np.asarray(ids, np.int64)] = np.asarray(rows,
                                                          np.float32)
                folded_rows += int(ids.shape[0])
        new_models = dict(model.models)
        for cid, t in tables.items():
            new_models[cid] = dataclasses.replace(new_models[cid],
                                                  means=t)
        compacted = dataclasses.replace(model, models=new_models)
        out = self.publish(
            compacted, model_version=versions[-1],
            extra={"compacted_from": gen,
                   "deltas_folded": versions})
        logger.info("compacted %d delta(s) (v%d..v%d, %d row(s)) into "
                    "gen-%06d", len(versions), versions[0], versions[-1],
                    folded_rows, out[0])
        return out


def publish_generation(model_dir: str, root: str,
                       model_version: int = 0) -> tuple[int, str]:
    """Convenience: load an npz GameModel directory and publish it as
    the next generation of ``root`` (the ``photon-game-publish
    --compact-generations`` bootstrap and dev-scripts' one-liner)."""
    from photon_ml_tpu.models import io as model_io

    model = model_io.load_game_model(model_dir, host=True)
    return GenerationStore(root).publish(model,
                                         model_version=model_version)
