"""Columnar mmap model format: boot is an ``mmap()``, not a parse.

One committed mapped-model directory holds a full GameModel::

    blobs/<cid>.bin   # ALL of one coordinate's persisted arrays as one
                      # 64-byte-aligned blob (the ingest-cache layout:
                      # one file per coordinate, one open + one mmap per
                      # coordinate at boot, one sequential extent for
                      # the page cache)
    blobs/<cid>.ok    # that blob's commit marker: column directory
                      # (name/dtype/shape/offset), the blob's CRC32
                      # taken over the good bytes, and the coordinate's
                      # models/io metadata — written atomically AFTER
                      # the blob
    model.json        # the DIRECTORY-LEVEL commit point, written LAST:
                      # format version, task, coordinate list, optional
                      # publisher metadata (generation, folded delta
                      # version). A directory without it does not exist.

The arrays inside a blob are exactly ``models/io.coordinate_arrays`` —
the ONE definition of "the model's bytes", shared with the npz writer
and the cross-rank digest — so a mapped load is bit-identical to the
npz load by construction (``game_model_digest`` equality is the tested
contract, not a tolerance).

Crash/corruption discipline (the ``utils/diskio`` v3 contract): every
file write is atomic, a kill anywhere before ``model.json`` leaves an
invisible directory (the previous generation stays fully servable), and
silent bit rot fails the committed CRC at load time and raises the
defined :class:`MapCorrupt` — the generation store's cue to fall back
one generation (``BootRecovered``) instead of serving garbage rows.

Fault sites (docs/ROBUSTNESS.md): ``boot.map_write`` is the crash seam
(occurrence 1 = before any blob, occurrence 2 = the torn window between
the last blob and the directory marker); ``boot.map_open`` is the
corruption seam (injected rot lands AFTER the checksum, the shape a
load must catch).
"""

from __future__ import annotations

import json
import logging
import mmap as _mmap
import os
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu.utils.diskio import atomic_write, file_crc32

logger = logging.getLogger("photon_ml_tpu.boot")

MAP_FORMAT = "photon-map"
MAP_FORMAT_VERSION = 1

_BLOBS = "blobs"
_MARKER = "model.json"
_ALIGN = 64  # column sections start on cache-line boundaries


class MapFormatError(RuntimeError):
    """The directory is not a committed mapped model (marker missing,
    torn, or from an unknown format version)."""


class MapCorrupt(MapFormatError):
    """A committed blob's bytes fail their CRC32 (or a column directory
    does not describe the blob) — never served, by construction."""


def is_mapped_model(path: str) -> bool:
    """Cheap layout probe: a committed ``model.json`` marker of OUR
    format (the npz layout's ``metadata.json`` never matches)."""
    marker = os.path.join(path, _MARKER)
    if not os.path.exists(marker):
        return False
    try:
        with open(marker) as f:
            return json.load(f).get("format") == MAP_FORMAT
    except (OSError, ValueError):
        return False


def is_mapped_array(a) -> bool:
    """True when ``a`` is (a view over) a memory-mapped buffer — the
    host store's zero-copy capability probe."""
    seen = set()
    while a is not None and id(a) not in seen:
        seen.add(id(a))
        if isinstance(a, (np.memmap, _mmap.mmap)):
            return True
        a = getattr(a, "base", None)
    return False


# -- write -------------------------------------------------------------------


def _pack_blob(arrays: dict[str, np.ndarray]) -> tuple[list, list, int]:
    """(column directory, byte pieces, total bytes) for one blob —
    the ingest cache's aligned packing, column names sorted so two
    writes of the same model are byte-identical files."""
    cols = []
    pieces: list[bytes] = []
    pos = 0
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        pad = (-pos) % _ALIGN
        if pad:
            pieces.append(b"\x00" * pad)
            pos += pad
        cols.append({"name": name, "dtype": a.dtype.str,
                     "shape": list(a.shape), "offset": pos})
        pieces.append(a.tobytes())
        pos += a.nbytes
    return cols, pieces, pos


def write_mapped_model(model, path: str,
                       extra: Optional[dict] = None) -> None:
    """Commit ``model`` as one mapped-model directory.

    Blobs first (atomic, per-blob CRC ``.ok`` markers), the directory
    marker LAST — a kill anywhere in between leaves no committed model.
    ``extra`` rides in the marker (the generation store stamps its
    generation number and the folded delta version there).
    """
    from photon_ml_tpu.models import io as model_io
    from photon_ml_tpu.types import TaskType

    flt.fire(flt.sites.BOOT_MAP_WRITE)
    blob_dir = os.path.join(path, _BLOBS)
    os.makedirs(blob_dir, exist_ok=True)
    coords = {}
    for cid in sorted(model.models):
        m = model.models[cid]
        meta = model_io.coordinate_meta(m)
        cols, pieces, nbytes = _pack_blob(model_io.coordinate_arrays(m))
        blob_path = os.path.join(blob_dir, f"{cid}.bin")
        atomic_write(blob_path, lambda f: f.writelines(pieces))
        crc = file_crc32(blob_path)
        # Injected bit rot lands AFTER the checksum was taken over the
        # good bytes — the corruption shape a boot-time load must catch.
        flt.corrupt_file(flt.sites.BOOT_MAP_OPEN, blob_path)
        marker = json.dumps({"version": MAP_FORMAT_VERSION, "meta": meta,
                             "cols": cols, "crc": crc,
                             "nbytes": nbytes}).encode()
        atomic_write(os.path.join(blob_dir, f"{cid}.ok"),
                     lambda f: f.write(marker))
        coords[cid] = meta
    # Occurrence 2 of the crash seam: every blob committed, directory
    # marker not — THE torn window a mid-publish SIGKILL must leave
    # invisible (the generation store's atomicity test drives it).
    flt.fire(flt.sites.BOOT_MAP_WRITE)
    body = json.dumps({
        "format": MAP_FORMAT,
        "version": MAP_FORMAT_VERSION,
        "task": TaskType(model.task).value,
        "coordinates": coords,
        **(extra or {}),
    }, indent=2, sort_keys=True).encode()
    atomic_write(os.path.join(path, _MARKER), lambda f: f.write(body))
    logger.info("mapped model committed: %d coordinate(s) -> %s",
                len(coords), path)


# -- read --------------------------------------------------------------------


def read_marker(path: str) -> dict:
    """The directory-level commit marker (raises :class:`MapFormatError`
    when absent/torn/from an unknown version — the caller's cue that
    this directory does not hold a committed mapped model)."""
    marker = os.path.join(path, _MARKER)
    if not os.path.exists(marker):
        raise MapFormatError(
            f"{path} has no committed {_MARKER} marker — torn or absent "
            f"publish")
    try:
        with open(marker) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise MapFormatError(f"{path} marker unreadable "
                             f"({type(e).__name__}: {e})")
    if meta.get("format") != MAP_FORMAT \
            or int(meta.get("version", -1)) > MAP_FORMAT_VERSION:
        raise MapFormatError(
            f"{path} is not a photon-map model this build can read "
            f"(format={meta.get('format')!r} "
            f"version={meta.get('version')!r})")
    return meta


def _map_blob(blob_dir: str, cid: str, verify: bool
              ) -> tuple[dict, dict[str, np.ndarray]]:
    """One coordinate's (models/io metadata, column name → read-only
    mmap-backed array). The CRC pass is ONE sequential read with no
    decode/copy; the arrays themselves stay lazy views over the page
    cache."""
    ok_path = os.path.join(blob_dir, f"{cid}.ok")
    blob_path = os.path.join(blob_dir, f"{cid}.bin")
    try:
        with open(ok_path) as f:
            marker = json.load(f)
    except (OSError, ValueError) as e:
        raise MapCorrupt(f"{blob_path} has no trustworthy commit marker "
                         f"({type(e).__name__}: {e})")
    if verify:
        try:
            got = file_crc32(blob_path)
        except OSError as e:
            raise MapCorrupt(f"{blob_path} unreadable "
                             f"({type(e).__name__}: {e})")
        if got != int(marker["crc"]):
            raise MapCorrupt(
                f"{blob_path} fails its committed CRC (got {got:#010x}, "
                f"marker {int(marker['crc']):#010x}) — refusing to "
                f"serve corrupt coefficient rows")
    # PML016 note: np.memmap's lifetime is refcounted through the array
    # views handed to the model — the last view dropping closes the map.
    blob = np.memmap(blob_path, dtype=np.uint8, mode="r",
                     shape=(int(marker["nbytes"]),))
    arrays = {}
    for col in marker["cols"]:
        dt = np.dtype(col["dtype"])
        count = int(np.prod(col["shape"], dtype=np.int64))
        arr = np.frombuffer(blob, dtype=dt, count=count,
                            offset=int(col["offset"]))
        arrays[col["name"]] = arr.reshape(col["shape"])
    return marker["meta"], arrays


def load_mapped_model(path: str, verify: bool = True):
    """Zero-copy load of a committed mapped model.

    Returns ``(GameModel, marker)`` — every coefficient table a
    read-only view over its blob's mmap (host numpy, exactly the
    ``load_game_model(host=True)`` contract), ``marker`` the directory
    metadata (generation / model_version when a generation store wrote
    it). Raises :class:`MapFormatError` / :class:`MapCorrupt`; never
    returns a partially trusted model.
    """
    from photon_ml_tpu.game.factored import FactoredRandomEffectModel
    from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                           RandomEffectModel,
                                           SubspaceRandomEffectModel)
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.types import TaskType

    marker = read_marker(path)
    blob_dir = os.path.join(path, _BLOBS)
    models = {}
    for cid, info in marker["coordinates"].items():
        meta, arrs = _map_blob(blob_dir, cid, verify)
        if meta != info:
            raise MapCorrupt(
                f"{path} blob {cid!r} metadata disagrees with the "
                f"directory marker — mixed-generation directory")
        kind = info["type"]
        if kind == "fixed":
            models[cid] = FixedEffectModel(
                shard_id=info["shard_id"],
                coefficients=Coefficients(
                    means=arrs["means"],
                    variances=arrs.get("variances")))
        elif kind == "factored":
            models[cid] = FactoredRandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard_id"],
                projection=arrs["projection"], factors=arrs["factors"])
        elif kind == "random-subspace":
            models[cid] = SubspaceRandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard_id"],
                num_features=int(info["dim"]),
                cols=arrs["cols"], means=arrs["means"],
                variances=arrs.get("variances"))
        elif kind == "random":
            models[cid] = RandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard_id"],
                means=arrs["means"], variances=arrs.get("variances"))
        else:
            raise MapFormatError(
                f"{path} blob {cid!r} has unknown coordinate type "
                f"{kind!r}")
    return GameModel(task=TaskType(marker["task"]), models=models), marker
