"""photon-obs: unified span tracing + cross-stack metrics (ISSUE 7).

One process-wide switch, off by default. When off, every instrumented
site pays exactly one ``None`` check (the photon-fault discipline); when
on, the stack produces:

* a Chrome trace-event JSON timeline (``chrome://tracing`` / Perfetto)
  of hierarchical spans — lifecycle scopes bridged from the existing
  Start/Finish events plus explicit spans in the hot seams (chunk
  transfer, psum merge, L-BFGS iterations, checkpoint writes, per-entity
  fit waves, batcher flushes);
* a Prometheus-text metrics registry — transfer byte/second accounting
  from the ``device_put`` wrapper, compile-cache miss counts, the peak
  in-flight chunk gauge, and retry/straggler/recovery counters fed from
  the event stream.

Entry points: ``game_train --trace-out trace.json --metrics-dump m.prom``,
``GameEstimator(trace=...)``, ``photon-obs summarize trace.json``. See
docs/OBSERVABILITY.md.

Import cost: pure stdlib + numpy — no JAX — so the lint CLI and bare
package imports stay fast.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from photon_ml_tpu.obs.bridge import (EventSpanBridge, install_bridge,
                                      installed_bridge, uninstall_bridge)
from photon_ml_tpu.obs.ledger import RunLedger
from photon_ml_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                       MetricsRegistry, metric_value,
                                       parse_prometheus_text)
from photon_ml_tpu.obs.trace import Span, Tracer, WorkerTracer
from photon_ml_tpu.obs.watchdog import (ConvergenceWatchdog, WatchdogConfig,
                                        WatchdogError,
                                        parse_watchdog_config)

__all__ = [
    "ConvergenceWatchdog", "Counter", "EventSpanBridge", "Gauge",
    "Histogram", "MetricsRegistry", "RunLedger", "Span", "Tracer",
    "WatchdogConfig", "WatchdogError", "WorkerTracer", "activated",
    "adopt_worker_context", "disable", "dump_trace", "enable",
    "install_bridge", "installed_bridge", "instant", "ledger",
    "metric_value", "metrics", "parse_prometheus_text",
    "parse_watchdog_config", "set_ledger", "set_watchdog", "span",
    "tracer", "uninstall_bridge", "watchdog_config", "worker_context",
]

_LOCK = threading.Lock()
_TRACER: Optional[Tracer] = None
_METRICS: Optional[MetricsRegistry] = None
_LEDGER: Optional[RunLedger] = None
_WATCHDOG: Optional[WatchdogConfig] = None


def tracer() -> Optional[Tracer]:
    """The active tracer, or None when tracing is off — THE hot-path
    check: ``tr = obs.tracer();  if tr is not None: ...``."""
    return _TRACER


def metrics() -> Optional[MetricsRegistry]:
    """The active metrics registry, or None when metrics are off."""
    return _METRICS


def ledger() -> Optional[RunLedger]:
    """The active run ledger, or None when no run is being recorded —
    the ledger sites' one None check (``led = obs.ledger(); if led is
    not None: led.record(...)``)."""
    return _LEDGER


def set_ledger(led: Optional[RunLedger]) -> Optional[RunLedger]:
    """Install ``led`` process-wide (None uninstalls); returns the
    PREVIOUS ledger so callers can restore it. The installer owns the
    lifecycle — close() in a finally (a crashed run keeps its prefix)."""
    global _LEDGER
    with _LOCK:
        prev, _LEDGER = _LEDGER, led
    return prev


def watchdog_config() -> Optional[WatchdogConfig]:
    """The installed convergence-watchdog config, or None (watchdogs
    off — the default; each optimizer site pays one None check)."""
    return _WATCHDOG


def set_watchdog(cfg: Optional[WatchdogConfig]
                 ) -> Optional[WatchdogConfig]:
    """Install ``cfg`` process-wide (None disarms); returns the
    previous config for restore."""
    global _WATCHDOG
    with _LOCK:
        prev, _WATCHDOG = _WATCHDOG, cfg
    return prev


def enable(trace: bool = True, metrics: bool = True,
           spill: Optional[str] = None
           ) -> tuple[Optional[Tracer], Optional[MetricsRegistry]]:
    """Turn observability on process-wide and install the event bridge.
    ``spill`` names the JSONL side-channel spawn-pool workers append
    their spans to (defaults to in-process tracing only)."""
    global _TRACER, _METRICS
    with _LOCK:
        if trace and _TRACER is None:
            t = Tracer(spill_path=spill)
            t.mark_spill_owner()
            _TRACER = t
        if metrics and _METRICS is None:
            _METRICS = MetricsRegistry()
    install_bridge()
    return _TRACER, _METRICS


def disable() -> None:
    """Turn observability off and detach the bridge (closing any
    lifecycle spans it still holds open)."""
    global _TRACER, _METRICS
    uninstall_bridge()
    with _LOCK:
        _TRACER = None
        _METRICS = None


@contextlib.contextmanager
def activated(trace_obj: Optional[Tracer] = None,
              metrics_obj: Optional[MetricsRegistry] = None):
    """Scope-local activation (``GameEstimator(trace=...)``): install the
    given tracer/registry for the duration, restore the previous state
    after — nested activations and an already-enabled process both
    compose (the outermost objects win; an explicit inner tracer
    temporarily replaces them)."""
    global _TRACER, _METRICS
    with _LOCK:
        prev_t, prev_m = _TRACER, _METRICS
        if trace_obj is not None:
            _TRACER = trace_obj
        if metrics_obj is not None:
            _METRICS = metrics_obj
    install_bridge()
    try:
        yield (_TRACER, _METRICS)
    finally:
        with _LOCK:
            _TRACER, _METRICS = prev_t, prev_m
        if prev_t is None and prev_m is None:
            uninstall_bridge()


_NULL_CM = contextlib.nullcontext()


def span(name: str, cat: str = "app", **args):
    """A span on the active tracer, or a shared no-op context manager
    when tracing is off — the one-line instrumentation helper for sites
    that don't want to hold a tracer reference."""
    t = _TRACER
    if t is None:
        return _NULL_CM
    return t.span(name, cat=cat, **args)


def instant(name: str, cat: str = "app", **args) -> None:
    t = _TRACER
    if t is not None:
        t.instant(name, cat=cat, **args)


def dump_trace(path: str) -> None:
    """Write the active tracer's Chrome trace JSON (bridge pairing stats
    ride along in ``otherData`` so smoke checks can assert zero leaks)."""
    t = _TRACER
    if t is None:
        return
    b = installed_bridge()
    t.dump(path, other_data=b.stats() if b is not None else None)


def dump_metrics(path: str) -> None:
    m = _METRICS
    if m is not None:
        m.dump(path)


# -- spawn-pool propagation (utils/workers.py) ----------------------------


def worker_context() -> Optional[dict]:
    """Driver-side: what a spawn-pool worker needs to keep tracing —
    the spill path and the submitting span as the worker's root parent.
    None when tracing is off or has nowhere to spill."""
    t = _TRACER
    if t is None or t.spill_path is None:
        return None
    return {"spill": t.spill_path, "parent": t.current()}


def adopt_worker_context(ctx: dict) -> None:
    """Worker-side (from the pool initializer): install a process-local
    spilling tracer parented under the driver span that built the pool."""
    global _TRACER
    with _LOCK:
        if _TRACER is None:
            _TRACER = WorkerTracer(label="worker",
                                   spill_path=ctx.get("spill"),
                                   default_parent=ctx.get("parent"))
