"""Span/Tracer hierarchical tracing core (docs/OBSERVABILITY.md).

Reference parity: none directly — the reference leans on the Spark UI's
stage timeline for "where did the time go". This repo's answer so far was
``jax.profiler`` (device-side HLO timelines via ``--profile-dir``), which
cannot see the HOST-side structure that dominates its open questions: the
n=100M streamed sweep is ~95% host→device transfer by hand-computed
subtraction, and nothing measures in-flight pipeline state. This module is
the host-side counterpart: hierarchical spans on monotonic clocks,
exported as Chrome trace-event JSON (loadable in ``chrome://tracing`` or
https://ui.perfetto.dev).

Design rules:

* **Finally-safe by construction** — the blessed API is the context
  manager (``with tracer.span("name"): ...``); the raw pair
  (``tracer.start()`` / ``Span.end()``) exists only for bridge-style code
  whose open and close live in different callbacks, and is linted
  (PML009) everywhere else.
* **Monotonic durations, wall-clock anchors** — a span's duration comes
  off ``time.perf_counter()`` (PML004: an NTP step must not dent a
  measurement); its POSITION on the timeline is anchored by one
  ``time.time_ns()`` timestamp so spans from different PROCESSES (spawn
  pool workers) land on one comparable axis.
* **Contextvar parenting** — the current span lives in a
  ``contextvars.ContextVar``, so nesting follows the call structure, not
  the class structure, and thread pools propagate it by running tasks
  under a copied context (``utils/workers.make_pool``). Spawn-pool
  workers cannot share the driver's tracer object; they adopt a
  process-local tracer that SPILLS finished spans to a shared JSONL file
  (one atomic appended line per span) which the driver merges at export.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from typing import Optional

# The current span (an id string), propagated by contextvar so nesting
# follows the call structure across `with` scopes and copied contexts.
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "photon_obs_current_span", default=None)


class Span:
    """One timed scope. Use as a context manager, or close with
    :meth:`end` from a ``finally`` (anything else is PML009)."""

    __slots__ = ("tracer", "name", "cat", "span_id", "parent_id", "args",
                 "tid", "t0_perf", "t0_epoch_ns", "dur", "_token", "_done")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 span_id: str, parent_id: Optional[str], args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.span_id = span_id
        self.parent_id = parent_id
        self.args = args
        self.tid = threading.get_ident()
        # Duration base is monotonic (PML004); the epoch stamp is a
        # TIMESTAMP anchoring the span on the cross-process time axis.
        self.t0_perf = time.perf_counter()
        self.t0_epoch_ns = time.time_ns()
        self.dur = None  # seconds; None while open
        self._token = _CURRENT.set(span_id)
        self._done = False
        tracer._opened(self)

    def set(self, **args) -> "Span":
        """Attach/overwrite span attributes (visible in the trace args)."""
        self.args.update(args)
        return self

    def end(self, **args) -> None:
        """Close the span (idempotent) and record it on the tracer."""
        if self._done:
            return
        self._done = True
        self.dur = time.perf_counter() - self.t0_perf
        if args:
            self.args.update(args)
        try:
            _CURRENT.reset(self._token)
        except ValueError:
            # Closed from a different context than it was opened in
            # (bridge pairs across callbacks): restore the parent
            # explicitly so later spans in THIS context nest correctly.
            _CURRENT.set(self.parent_id)
        self.tracer._record(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self.end()


class Tracer:
    """Process-local span recorder with Chrome trace-event export.

    ``spill_path`` makes finished spans ALSO append to a JSONL file —
    the cross-process merge channel for spawn-pool workers (each line is
    one complete Chrome event; O_APPEND keeps concurrent writers from
    interleaving). ``default_parent`` seeds the parent of root spans
    (a worker tracer parents its roots under the driver span that
    submitted the work).
    """

    def __init__(self, label: str = "driver",
                 spill_path: Optional[str] = None,
                 default_parent: Optional[str] = None):
        self.label = label
        self.spill_path = spill_path
        self.default_parent = default_parent
        self.pid = os.getpid()
        self.epoch_ns = time.time_ns()  # export time base (timestamp)
        self._lock = threading.Lock()
        self._finished: list[Span] = []
        self._live: dict[str, Span] = {}  # open spans, by id
        self._instants: list[dict] = []
        self._seq = 0
        self._started_total = 0

    # -- span lifecycle ----------------------------------------------------

    def _new_id(self) -> str:
        with self._lock:
            self._seq += 1
            self._started_total += 1
            return f"{self.pid:x}.{self._seq:x}"

    def _opened(self, span: Span) -> None:
        with self._lock:
            self._live[span.span_id] = span

    def span(self, name: str, cat: str = "app", **args) -> Span:
        """Open a span as a context manager (the blessed, finally-safe
        API): ``with tracer.span("stream.pass"): ...``."""
        parent = _CURRENT.get() or self.default_parent
        return Span(self, name, cat, self._new_id(), parent, dict(args))

    def start(self, name: str, cat: str = "app",
              parent: Optional[str] = None, **args) -> Span:
        """RAW begin — the caller owns the matching :meth:`Span.end`.
        Only for open/close pairs that cannot share a lexical scope
        (the event bridge); anywhere else use :meth:`span` (PML009)."""
        p = parent if parent is not None else (_CURRENT.get()
                                               or self.default_parent)
        return Span(self, name, cat, self._new_id(), p, dict(args))

    def instant(self, name: str, cat: str = "app", **args) -> None:
        """A zero-duration marker event (Chrome ``ph: "i"``)."""
        now_ns = time.time_ns()
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._ts_us(now_ns), "epoch_ns": now_ns,
              "pid": self.pid, "tid": threading.get_ident(),
              "args": args}
        with self._lock:
            self._instants.append(ev)
        self._spill(ev)

    def current(self) -> Optional[str]:
        """The current contextvar span id (the worker-ctx parent seed)."""
        return _CURRENT.get() or self.default_parent

    def record_complete(self, name: str, cat: str = "app", *,
                        t0_epoch_ns: int, dur_s: float,
                        parent: Optional[str] = None,
                        tid: Optional[int] = None, **args) -> str:
        """Record an already-measured interval as a CLOSED span.

        For queue-crossing scopes whose open and close are observed after
        the fact from measured timestamps — a serving request's life from
        enqueue (HTTP handler thread) to respond (batcher worker thread)
        is attributed in one place, AFTER the interval ended, so there is
        no live ``Span`` to carry across threads. The span never touches
        the contextvar (nothing can nest "inside" a finished interval)
        and needs no ``end()``: it is born closed. Returns the span id so
        callers can parent attribution children under it.
        """
        sp = object.__new__(Span)
        sp.tracer = self
        sp.name = name
        sp.cat = cat
        sp.span_id = self._new_id()
        sp.parent_id = parent
        sp.args = dict(args)
        sp.tid = threading.get_ident() if tid is None else tid
        sp.t0_perf = 0.0  # unused: dur is explicit
        sp.t0_epoch_ns = int(t0_epoch_ns)
        sp.dur = float(dur_s)
        sp._token = None
        sp._done = True
        with self._lock:
            self._finished.append(sp)
        if self.spill_path is not None:  # skip the event build otherwise
            self._spill(self._event(sp))
        return sp.span_id

    def _record(self, span: Span) -> None:
        with self._lock:
            self._live.pop(span.span_id, None)
            self._finished.append(span)
        self._spill(self._event(span))

    # -- export ------------------------------------------------------------

    def _ts_us(self, epoch_ns: int) -> float:
        return (epoch_ns - self.epoch_ns) / 1e3

    def _event(self, sp: Span, unfinished: bool = False) -> dict:
        dur = sp.dur if sp.dur is not None \
            else time.perf_counter() - sp.t0_perf
        args = dict(sp.args)
        args["span_id"] = sp.span_id
        if sp.parent_id is not None:
            args["parent_id"] = sp.parent_id
        if unfinished:
            args["unfinished"] = True
        return {"name": sp.name, "cat": sp.cat, "ph": "X",
                "ts": self._ts_us(sp.t0_epoch_ns), "dur": dur * 1e6,
                "pid": self.pid, "tid": sp.tid, "args": args}

    def open_spans(self) -> int:
        with self._lock:
            return len(self._live)

    def chrome_trace(self, other_data: Optional[dict] = None) -> dict:
        """The full Chrome trace-event JSON object: finished spans,
        instants, spilled worker-process spans, and process/thread
        metadata. Unclosed spans export with ``args.unfinished`` so
        ``photon-obs verify`` can flag the leak instead of hiding it."""
        with self._lock:
            finished = list(self._finished)
            live = list(self._live.values())
            instants = list(self._instants)
            open_count = len(live)
            started = self._started_total
        events: list[dict] = [{
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "args": {"name": f"photon {self.label}"}}]
        events += [self._event(sp) for sp in finished]
        events += [self._event(sp, unfinished=True) for sp in live]
        events += instants
        events += self._read_spill()
        meta = {"open_spans": open_count, "spans_started": started,
                "clock_epoch_ns": self.epoch_ns}
        if other_data:
            meta.update(other_data)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": meta}

    def dump(self, path: str, other_data: Optional[dict] = None) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(other_data), f, default=str)
        os.replace(tmp, path)

    # -- cross-process spill -----------------------------------------------

    def _spill(self, event: dict) -> None:
        if self.spill_path is None or self.pid == _SPILL_OWNER_PID.get(
                self.spill_path):
            return
        try:
            line = json.dumps(event, default=str) + "\n"
            # One O_APPEND write per line: concurrent worker processes
            # append whole lines without interleaving.
            fd = os.open(self.spill_path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                os.write(fd, line.encode())
            finally:
                os.close(fd)
        except OSError as e:
            # Tracing must never take down the work it observes.
            import logging

            logging.getLogger("photon_ml_tpu.obs").warning(
                "span spill to %s failed: %s", self.spill_path, e)

    def _read_spill(self) -> list[dict]:
        if self.spill_path is None or not os.path.exists(self.spill_path):
            return []
        out = []
        with open(self.spill_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = dict(json.loads(line))
                except ValueError:
                    continue  # torn tail line from a killed worker
                # Worker clocks anchor on the epoch; rebase onto ours.
                if "epoch_ns" in ev:
                    ev["ts"] = self._ts_us(int(ev.pop("epoch_ns")))
                out.append(ev)
        return out

    def mark_spill_owner(self) -> None:
        """Record that THIS process owns the spill file (the driver): its
        own spans stay in memory; only other processes append."""
        if self.spill_path is not None:
            _SPILL_OWNER_PID[self.spill_path] = self.pid
            try:
                # Stale content from a previous run must not merge into
                # this one's export (workers recreate the file lazily).
                os.remove(self.spill_path)
            except OSError:
                pass  # absent is the normal case


# spill_path → owning (driver) pid; workers never match and thus spill.
_SPILL_OWNER_PID: dict = {}


class WorkerTracer(Tracer):
    """A spawn-pool worker's tracer: every finished span goes straight to
    the spill file with an absolute epoch stamp (the driver rebases onto
    its own clock at export)."""

    def _event(self, sp: Span, unfinished: bool = False) -> dict:
        ev = super()._event(sp, unfinished)
        # Ship the absolute stamp; the driver's ``ts`` base differs.
        ev["epoch_ns"] = sp.t0_epoch_ns
        ev.pop("ts", None)
        return ev
