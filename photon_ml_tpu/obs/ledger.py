"""photon-ledger: the run ledger — convergence telemetry on disk.

The papers this system reproduces report convergence-vs-wall-clock curves
as their primary evidence (Snap ML's stage-attributed measurements,
Trofimov–Genkin's distributed coordinate descent — PAPERS.md), yet until
ISSUE 9 a fit's per-iteration trajectory lived only in compiled
NaN-padded ``OptResult`` histories dropped on the floor. The run ledger
is the durable form: every ``GameEstimator.fit`` / ``game_train`` run
writes, under one directory,

* ``manifest.json`` + ``manifest.ok`` — run id, creator-supplied config,
  mesh shape, code/env versions, and the run IDENTITY stamped from
  ``game/descent.py``'s checkpoint-fingerprint machinery (task, update
  sequence, dataset digest — everything that makes a ``--resume`` run
  THE SAME run). Committed under the repo's atomic-marker/CRC discipline
  (utils/diskio.py): the ``.ok`` marker carries the manifest's CRC32 and
  is written last.
* ``telemetry.jsonl`` — append-as-produced rows, one JSON object per
  line, each carrying a contiguous ``seq`` and its own CRC32. A crashed
  or SIGKILL'd run keeps its curve: the reader validates row CRCs and
  returns the longest clean prefix, and ``RunLedger.resume`` truncates a
  torn tail before appending — monotone ``seq`` across the crash.

Row kinds (all carry ``seq``, ``t`` = seconds since the run began,
monotone across resumes, plus any context bound by the driver —
coordinate, outer iteration, descent step, grid point, tuning trial):

* ``opt_iter`` — one optimizer iteration: objective value, gradient
  norm, step size, probe/pass counts, per-iteration wall seconds, and
  cumulative transfer byte/second counters read from the photon-obs
  registry. The streaming driver loop records these LIVE per accepted
  iteration; the compiled L-BFGS/TRON paths spill their
  ``value_history``/``grad_norm_history`` post-fit (``clock:
  "post_fit"`` — wall resolution is then the coordinate update, not the
  iteration).
* ``coordinate_update`` — one descent step: coordinate, seconds,
  validation metrics.
* ``re_fit_wave`` — one vmapped random-effect fit-wave dispatch:
  re_type, wave index, seconds, ``entities_fit``/``entities_skipped``
  lane counts, and (gated sweeps, docs/SWEEPS.md) ``drift_p99`` — the
  p99 per-entity residual-offset drift the gate saw this sweep.
* ``tuning_trial`` — one hyperparameter trial: sampled point, expected
  improvement (GP search), objective, wall seconds.
* ``watchdog`` — a convergence-watchdog alert (obs/watchdog.py).
* ``publish`` — one continuous-publication ladder phase
  (serving/publish.py): ``refit`` / ``delta_write`` / ``canary_apply``
  / ``canary_verdict`` / ``swap`` / ``rollback`` / ``published`` /
  ``reapply`` rows carrying the delta version and verdict context,
  appended as produced like every other kind — ``photon-obs tail
  --publish`` renders the ladder.
* ``run_end`` — clean shutdown marker (its absence means the run is
  live or was killed — ``photon-obs tail`` reports exactly that).

Writers go through the BUFFERED ``RunLedger.record`` API — never raw
``open``/``json.dump`` in an optimizer loop (PML010 mechanizes this, the
PML001 host-sync discipline applied to telemetry I/O).

Import cost: pure stdlib — no JAX, no numpy — so ``photon-obs
tail``/``diff``/``verify`` run anywhere the lint CLI does.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import threading
import time
import uuid
import zlib
from typing import Optional

logger = logging.getLogger("photon_ml_tpu.obs")

LEDGER_VERSION = 1
_MANIFEST = "manifest.json"
_MANIFEST_OK = "manifest.ok"
_TELEMETRY = "telemetry.jsonl"

# Keys of a game/descent.py checkpoint fingerprint that define RUN
# identity — everything that makes "the same run" except the
# per-coordinate optimizer configs (a reg-weight grid / tuning sweep is
# ONE run whose trials share a ledger; the full per-config digests are
# recorded separately under manifest["fingerprints"] for forensics).
_IDENTITY_KEYS = ("task", "sequence", "iterations", "locked", "num_rows",
                  "data_digest")


class LedgerError(RuntimeError):
    """A ledger that cannot be trusted (bad manifest CRC, identity
    mismatch on an explicit resume, unwritable directory)."""


def _canonical(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _digest(obj) -> str:
    return hashlib.sha1(_canonical(obj).encode()).hexdigest()


def _coerce(value):
    """Field values must survive a JSON round trip byte-identically (the
    row CRC is over the re-serialized object) — coerce numpy/JAX scalars
    and tuples to plain Python."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _coerce(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_coerce(v) for v in value]
    try:
        return float(value)
    except (TypeError, ValueError):
        return str(value)


def row_crc(row: dict) -> int:
    """CRC32 of a row's canonical JSON, excluding the ``crc`` field
    itself (the writer and the reader must agree on this)."""
    payload = {k: v for k, v in row.items() if k != "crc"}
    return zlib.crc32(_canonical(payload).encode()) & 0xFFFFFFFF


def build_manifest(*, config: Optional[dict] = None,
                   mesh_shape: Optional[dict] = None,
                   extra: Optional[dict] = None) -> dict:
    """A fresh manifest skeleton: run id + creation stamp + code/env
    versions + whatever configuration the creator can describe. The run
    IDENTITY is stamped later by the first ``bind_fingerprint`` call
    (game/descent.py's machinery — the creator rarely knows the dataset
    digest up front)."""
    import platform
    import sys

    versions = {"python": platform.python_version(),
                "photon_ml_tpu": "dev"}
    for mod in ("jax", "numpy"):
        m = sys.modules.get(mod)
        v = getattr(m, "__version__", None) if m is not None else None
        if v is not None:
            versions[mod] = v
    manifest = {
        "version": LEDGER_VERSION,
        "run_id": uuid.uuid4().hex,
        "created_unix": time.time(),
        "config": _coerce(config or {}),
        "mesh_shape": _coerce(mesh_shape or {}),
        "versions": versions,
        "fingerprints": {},
    }
    if extra:
        manifest.update(_coerce(extra))
    return manifest


def identity_of(fingerprint: dict) -> str:
    """The run-identity digest of a descent checkpoint fingerprint —
    the subset that survives grid/tuning config swaps."""
    return _digest({k: fingerprint.get(k) for k in _IDENTITY_KEYS})


class RunLedger:
    """One training run's manifest + append-as-produced telemetry.

    Thread-safe for ``record``; the driver loop is the intended single
    writer, but RE wave rows and event listeners may land from helper
    threads. Use :meth:`resume` to open (it creates when absent), bind
    run identity via :meth:`bind_fingerprint`, and ``close()`` in a
    ``finally`` — a crashed run's ledger is still a valid prefix.
    """

    def __init__(self, directory: str, manifest: dict, *,
                 seq: int = 0, t_base: float = 0.0, fh=None,
                 flush_rows: int = 1):
        self.directory = directory
        self.manifest = manifest
        self._seq = seq
        self._t_base = t_base
        self._anchor = time.perf_counter()
        self._fh = fh
        self._lock = threading.Lock()
        self._ctx: dict = {}
        self._buf: list[str] = []
        # Rows buffered before an fsync-free append. 1 = append-as-
        # produced (the per-iteration default: one line per seconds-long
        # optimizer iteration); raise it for high-rate producers.
        self.flush_rows = max(1, int(flush_rows))
        self.closed = False

    # -- construction --------------------------------------------------------

    @classmethod
    def create(cls, directory: str, manifest: Optional[dict] = None,
               **manifest_kwargs) -> "RunLedger":
        """Start a FRESH ledger (truncates any previous telemetry)."""
        os.makedirs(directory, exist_ok=True)
        manifest = manifest or build_manifest(**manifest_kwargs)
        led = cls(directory, manifest,
                  fh=open(os.path.join(directory, _TELEMETRY),  # pml: allow[PML013] telemetry is append-as-produced BY PROTOCOL: each row carries its own CRC32, readers take the longest clean prefix (module docstring)
                          "w"))
        led._commit_manifest()
        return led

    @classmethod
    def resume(cls, directory: str, manifest: Optional[dict] = None,
               **manifest_kwargs) -> "RunLedger":
        """Open for append — create when absent. A torn final line (the
        SIGKILL shape) is truncated away so appended rows continue the
        clean prefix with contiguous ``seq``. Identity validation
        happens at the first :meth:`bind_fingerprint`."""
        existing = read_manifest(directory)
        if existing is None:
            return cls.create(directory, manifest, **manifest_kwargs)
        path = os.path.join(directory, _TELEMETRY)
        rows, problems, clean_bytes = _scan_rows(path)
        if problems:
            logger.warning(
                "ledger %s telemetry has a torn/corrupt tail (%s) — "
                "truncating to the clean %d-row prefix", directory,
                "; ".join(problems), len(rows))
            with open(path, "r+b") as f:  # pml: allow[PML013] torn-tail repair truncates IN PLACE to the CRC-clean prefix; atomic_write would copy the whole stream
                f.truncate(clean_bytes)
        last = rows[-1] if rows else None
        fh = open(path, "a")  # pml: allow[PML013] resume APPENDS to the row-CRC'd stream — that is the protocol, not a raw artifact write
        led = cls(directory, existing,
                  seq=(int(last["seq"]) + 1) if last else 0,
                  t_base=float(last["t"]) if last else 0.0,
                  fh=fh)
        return led

    def _commit_manifest(self) -> None:
        """Atomic manifest + CRC-carrying ``.ok`` marker written LAST
        (the v3 commit discipline — utils/diskio.py)."""
        from photon_ml_tpu.utils.diskio import atomic_write

        path = os.path.join(self.directory, _MANIFEST)
        body = json.dumps(self.manifest, indent=2, sort_keys=True)
        atomic_write(path, lambda f: f.write(body.encode()))
        crc = zlib.crc32(body.encode()) & 0xFFFFFFFF
        atomic_write(os.path.join(self.directory, _MANIFEST_OK),
                     lambda f: f.write(json.dumps({"crc": crc}).encode()))

    # -- identity ------------------------------------------------------------

    def bind_fingerprint(self, fingerprint: dict,
                         key: Optional[str] = None) -> None:
        """Stamp (or validate) run identity from a descent checkpoint
        fingerprint. First bind stamps the manifest; a later bind — or a
        resumed ledger — must agree on the identity subset (task,
        sequence, dataset digest …) or the ledger RESETS loudly to a
        fresh run (mirroring CheckpointManager's fingerprint-mismatch
        discard: appending a different run's curve would be silently
        wrong data). The FULL per-config digest is recorded under
        ``fingerprints[key]`` for forensics, not validated — grid points
        and tuning trials are one run."""
        ident = identity_of(fingerprint)
        if key is None:
            key = f"grid-{self._ctx.get('grid', 0)}"
        with self._lock:
            have = self.manifest.get("identity")
            if have is not None and have != ident:
                logger.warning(
                    "ledger %s was written by a different run "
                    "(identity %s != %s) — starting a fresh ledger "
                    "(the old curve is discarded, like a fingerprint-"
                    "mismatched checkpoint)", self.directory, have[:12],
                    ident[:12])
                self._reset_locked()
            changed = False
            if self.manifest.get("identity") != ident:
                self.manifest["identity"] = ident
                changed = True
            fps = self.manifest.setdefault("fingerprints", {})
            if fps.get(key) != _digest(fingerprint):
                fps[key] = _digest(fingerprint)
                changed = True
            if changed:
                self._commit_manifest()

    def _reset_locked(self) -> None:
        self._flush_locked()
        self._fh.close()
        self.manifest["run_id"] = uuid.uuid4().hex
        self.manifest["created_unix"] = time.time()
        self.manifest.pop("identity", None)
        self.manifest["fingerprints"] = {}
        self._fh = open(os.path.join(self.directory, _TELEMETRY), "w")  # pml: allow[PML013] identity reset starts a FRESH append-as-produced stream (row CRCs, not atomic_write)
        self._seq = 0
        self._t_base = 0.0
        self._anchor = time.perf_counter()

    # -- writing -------------------------------------------------------------

    @contextlib.contextmanager
    def bound(self, **context):
        """Merge ``context`` into every row recorded inside the scope
        (the descent loop binds coordinate/outer_iteration/step; the
        estimator binds the grid point; tuning binds the trial)."""
        with self._lock:
            saved = {k: self._ctx.get(k, _MISSING) for k in context}
            self._ctx.update(context)
        try:
            yield self
        finally:
            with self._lock:
                for k, v in saved.items():
                    if v is _MISSING:
                        self._ctx.pop(k, None)
                    else:
                        self._ctx[k] = v

    def record(self, kind: str, **fields) -> None:
        """Append one telemetry row (buffered; see ``flush_rows``).
        THE write API for optimizer/descent loops — PML010."""
        with self._lock:
            if self.closed:
                return
            row = dict(self._ctx)
            row.update({k: _coerce(v) for k, v in fields.items()})
            row["seq"] = self._seq
            row["t"] = round(
                self._t_base + time.perf_counter() - self._anchor, 6)
            row["kind"] = kind
            row["crc"] = row_crc(row)
            self._seq += 1
            self._buf.append(_canonical(row))
            if len(self._buf) >= self.flush_rows:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf and self._fh is not None:
            self._fh.write("\n".join(self._buf) + "\n")
            self._fh.flush()
            self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self, status: str = "ok") -> None:
        """Flush and close; records a ``run_end`` marker so ``tail`` can
        tell a finished run from a killed one. Safe to call twice."""
        with self._lock:
            if self.closed:
                return
            # Inline run_end (record() would deadlock on the held lock).
            row = dict(self._ctx)
            row.update({"seq": self._seq, "kind": "run_end",
                        "status": status,
                        "t": round(self._t_base + time.perf_counter()
                                   - self._anchor, 6)})
            row["crc"] = row_crc(row)
            self._seq += 1
            self._buf.append(_canonical(row))
            self._flush_locked()
            self._fh.close()
            self.closed = True

    @property
    def telemetry_path(self) -> str:
        return os.path.join(self.directory, _TELEMETRY)


_MISSING = object()


def transfer_totals() -> dict:
    """Cumulative transfer counters from the active photon-obs registry
    (empty when metrics are off) — the opt_iter rows' provenance-shared
    transfer columns."""
    from photon_ml_tpu import obs

    mx = obs.metrics()
    if mx is None:
        return {}
    out = {}
    snap = mx.snapshot()
    for name, col in (("photon_transfer_bytes_total", "transfer_bytes"),
                      ("photon_transfer_seconds_total",
                       "transfer_seconds")):
        total = None
        for k, v in snap.items():
            if k == name or k.startswith(name + "{"):
                total = (total or 0.0) + v
        if total is not None:
            out[col] = total
    return out


def fabric_totals() -> dict:
    """Cumulative cross-host fabric counters from the active registry
    (empty when metrics are off, or when no fabric ever fired) — the
    ``fabric_digest`` rows' provenance columns: how many DCN rounds,
    retries, and bytes stand behind the digest being attested."""
    from photon_ml_tpu import obs

    mx = obs.metrics()
    if mx is None:
        return {}
    out = {}
    snap = mx.snapshot()
    for name, col in (("photon_fabric_allreduce_total",
                       "fabric_allreduces"),
                      ("photon_fabric_retries_total", "fabric_retries"),
                      ("photon_fabric_bytes_total", "fabric_bytes")):
        total = None
        for k, v in snap.items():
            if k == name or k.startswith(name + "{"):
                total = (total or 0.0) + v
        if total is not None:
            out[col] = total
    return out


def spill_history(led: "RunLedger", values, grad_norms,
                  opt: str = "compiled") -> int:
    """Spill a compiled optimizer's NaN-padded value/grad-norm histories
    as post-fit ``opt_iter`` rows (``clock: "post_fit"`` — row ``t`` is
    the spill time, so wall resolution is the coordinate update).
    Returns the number of rows written."""
    n = 0
    for i, (v, g) in enumerate(zip(values, grad_norms)):
        v, g = float(v), float(g)
        if v != v:  # NaN padding past the executed iterations
            continue
        led.record("opt_iter", opt=opt, clock="post_fit", iteration=i,
                   value=v, grad_norm=(None if g != g else g))
        n += 1
    return n


# -- reading ----------------------------------------------------------------


def read_manifest(directory: str) -> Optional[dict]:
    """The committed manifest, or None when absent. Raises LedgerError
    on a CRC mismatch (a half-written or bit-rotted manifest must not
    silently pass for the run's identity)."""
    path = os.path.join(directory, _MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        body = f.read()
    ok_path = os.path.join(directory, _MANIFEST_OK)
    if os.path.exists(ok_path):
        try:
            with open(ok_path) as f:
                want = int(json.load(f)["crc"])
        except (ValueError, KeyError, OSError) as e:
            raise LedgerError(
                f"ledger marker {ok_path} is unreadable "
                f"({type(e).__name__}: {e})")
        got = zlib.crc32(body.encode()) & 0xFFFFFFFF
        if got != want:
            raise LedgerError(
                f"ledger manifest {path} fails its committed CRC "
                f"(got {got}, marker {want}) — the manifest cannot be "
                f"trusted")
    try:
        return json.loads(body)
    except ValueError as e:
        raise LedgerError(f"ledger manifest {path} is not JSON: {e}")


def _scan_rows(path: str) -> tuple[list[dict], list[str], int]:
    """(clean-prefix rows, problems, byte length of the clean prefix).
    Stops at the first torn/corrupt/out-of-order line — everything
    before it is the trustworthy curve."""
    rows: list[dict] = []
    problems: list[str] = []
    clean = 0
    if not os.path.exists(path):
        return rows, ["telemetry.jsonl missing"], 0
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        nl = data.find(b"\n", pos)
        if nl == -1:
            # No trailing newline: a torn final line (SIGKILL mid-write).
            problems.append(f"torn final line at byte {pos}")
            break
        raw = data[pos:nl]
        pos = nl + 1
        if not raw.strip():
            clean = pos
            continue
        try:
            row = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            problems.append(f"unparseable row after seq "
                            f"{rows[-1]['seq'] if rows else 'start'}")
            break
        if not isinstance(row, dict) or row.get("crc") != row_crc(row):
            problems.append(f"row CRC mismatch at seq "
                            f"{row.get('seq') if isinstance(row, dict) else '?'}")
            break
        if int(row.get("seq", -1)) != len(rows):
            problems.append(
                f"non-contiguous seq {row.get('seq')} (expected "
                f"{len(rows)})")
            break
        if rows and float(row["t"]) < float(rows[-1]["t"]) - 1e-9:
            problems.append(f"non-monotone t at seq {row['seq']}")
            break
        rows.append(row)
        clean = pos
    return rows, problems, clean


def read_rows(directory: str) -> tuple[list[dict], list[str]]:
    """The clean-prefix telemetry rows of a ledger directory, plus any
    problems found past the prefix (a killed run reports its torn tail
    here while the curve stays usable)."""
    rows, problems, _ = _scan_rows(os.path.join(directory, _TELEMETRY))
    return rows, problems


def verify_ledger(directory: str) -> list[str]:
    """Structural health check (``photon-obs verify`` on a ledger dir):
    manifest present + CRC-committed, rows contiguous/monotone/CRC-clean
    to the end of the file. Empty list = healthy."""
    problems: list[str] = []
    try:
        manifest = read_manifest(directory)
    except LedgerError as e:
        return [str(e)]
    if manifest is None:
        return [f"no manifest.json under {directory}"]
    if not os.path.exists(os.path.join(directory, _MANIFEST_OK)):
        problems.append("manifest.ok CRC marker missing")
    rows, row_problems = read_rows(directory)
    problems.extend(row_problems)
    if not rows:
        problems.append("no telemetry rows")
    return problems


# -- curves / diffing --------------------------------------------------------


def convergence_curves(rows: list[dict]) -> dict:
    """Per-coordinate convergence curves from ``opt_iter`` rows:
    coordinate → list of {t, iteration, value, grad_norm, gap, passes}
    with ``passes`` the running streamed-pass total (value + gradient +
    dual passes; compiled spills count one pass per iteration) and
    ``gap`` the duality-gap certificate of the stochastic solvers
    (None on L-BFGS/TRON rows, which never emit one)."""
    curves: dict = {}
    passes_cum: dict = {}
    for row in rows:
        if row.get("kind") != "opt_iter" or row.get("value") is None:
            continue
        coord = row.get("coordinate") or "(run)"
        inc = float(row.get("value_passes") or 0) + \
            float(row.get("grad_passes") or 0) + \
            float(row.get("dual_passes") or 0)
        p = passes_cum.get(coord, 0.0) + (inc if inc > 0 else 1.0)
        passes_cum[coord] = p
        curves.setdefault(coord, []).append({
            "t": float(row["t"]),
            "iteration": int(row.get("iteration") or 0),
            "value": float(row["value"]),
            "grad_norm": (None if row.get("grad_norm") is None
                          else float(row["grad_norm"])),
            "gap": (None if row.get("gap") is None
                    else float(row["gap"])),
            "passes": p,
        })
    return curves


def time_to_target(curve: list[dict], target: float) -> Optional[dict]:
    """First point of ``curve`` whose value reached ``target`` (values
    are minimized). None when the run never got there. ``seconds`` is
    measured FROM THE CURVE START (so resumed ledgers and multi-phase
    scripts compare fairly); ``t`` is the raw ledger timestamp."""
    if not curve:
        return None
    t0 = curve[0]["t"]
    for pt in curve:
        if pt["value"] <= target:
            return {"seconds": round(pt["t"] - t0, 6), "t": pt["t"],
                    "passes": pt["passes"],
                    "iteration": pt["iteration"], "value": pt["value"]}
    return None


def time_to_fraction(curve: list[dict],
                     fraction: float = 0.99) -> Optional[dict]:
    """Time to achieve ``fraction`` of the run's own total objective
    drop — the flagship's self-contained ``time_to_target_value_seconds``
    definition (target = f_final + (1-fraction)·(f0 - f_final))."""
    if len(curve) < 2:
        return None
    f0, f_final = curve[0]["value"], curve[-1]["value"]
    if not f0 > f_final:
        return None
    target = f_final + (1.0 - fraction) * (f0 - f_final)
    out = time_to_target(curve, target)
    if out is not None:
        out["target_value"] = target
    return out


def _flatten(obj, prefix="") -> dict:
    out = {}
    if isinstance(obj, dict):
        for k in sorted(obj):
            out.update(_flatten(obj[k], f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = obj
    return out


def config_delta(manifest_a: dict, manifest_b: dict) -> list[dict]:
    """Flattened key-by-key differences of the two manifests' config +
    identity-adjacent fields (run_id/created/versions excluded — two
    runs of the same config should diff empty)."""
    skip = {"run_id", "created_unix", "fingerprints"}
    fa = _flatten({k: v for k, v in manifest_a.items() if k not in skip})
    fb = _flatten({k: v for k, v in manifest_b.items() if k not in skip})
    out = []
    for key in sorted(set(fa) | set(fb)):
        va, vb = fa.get(key), fb.get(key)
        if va != vb:
            out.append({"key": key, "a": va, "b": vb})
    return out


def final_validation_metrics(rows: list[dict]) -> dict:
    """Last observed validation metrics per coordinate (from
    ``coordinate_update`` rows)."""
    out: dict = {}
    for row in rows:
        if row.get("kind") == "coordinate_update" and row.get("validation"):
            out[row.get("coordinate") or "(run)"] = row["validation"]
    return out


def fit_wave_summary(rows: list[dict]) -> dict:
    """Per-(coordinate, outer iteration) aggregation of ``re_fit_wave``
    rows: lane counts fit/skipped, wave seconds, and the max drift_p99
    the gate saw. The ``photon-obs diff`` entities_fit overlay's data —
    recorded by every random-effect train call, gated or not."""
    agg: dict = {}
    for row in rows:
        if row.get("kind") != "re_fit_wave":
            continue
        coord = row.get("coordinate") or row.get("re_type") or "(run)"
        it = int(row.get("outer_iteration") or 0)
        e = agg.setdefault(coord, {}).setdefault(
            it, {"outer_iteration": it, "entities_fit": 0,
                 "entities_skipped": 0, "seconds": 0.0, "waves": 0,
                 "drift_p99": 0.0})
        e["entities_fit"] += int(row.get("entities_fit") or 0)
        e["entities_skipped"] += int(row.get("entities_skipped") or 0)
        e["seconds"] = round(e["seconds"] + float(row.get("seconds") or 0.0),
                             6)
        e["waves"] += 1
        e["drift_p99"] = max(e["drift_p99"],
                             float(row.get("drift_p99") or 0.0))
    return {coord: [per_it[k] for k in sorted(per_it)]
            for coord, per_it in agg.items()}


def diff_ledgers(dir_a: str, dir_b: str,
                 fraction: float = 0.99) -> dict:
    """Compare two run ledgers: config delta, per-coordinate
    time-to-target (target = the WORSE of the two final values, so both
    runs reached it), value-vs-wall / value-vs-passes curve overlays,
    and final value/metric deltas. The ``photon-obs diff`` engine, also
    consumed by check_bench_regression's convergence gate."""
    out: dict = {"a": dir_a, "b": dir_b}
    man_a, man_b = read_manifest(dir_a), read_manifest(dir_b)
    if man_a is None or man_b is None:
        raise LedgerError("both diff arguments must be ledger "
                          "directories with a committed manifest")
    rows_a, prob_a = read_rows(dir_a)
    rows_b, prob_b = read_rows(dir_b)
    out["problems"] = {"a": prob_a, "b": prob_b}
    out["run_ids"] = {"a": man_a.get("run_id"), "b": man_b.get("run_id")}
    out["config_delta"] = config_delta(man_a, man_b)
    def _rebased(curves: dict) -> dict:
        # Each curve on its own "seconds into the fit" axis: absolute
        # ledger time bakes in staging/compile offsets that differ run
        # to run and would skew the overlay and any x-axis comparison.
        return {coord: [dict(p, t=round(p["t"] - pts[0]["t"], 6))
                        for p in pts]
                for coord, pts in curves.items() if pts}

    curves_a = _rebased(convergence_curves(rows_a))
    curves_b = _rebased(convergence_curves(rows_b))
    coords: dict = {}
    for coord in sorted(set(curves_a) | set(curves_b)):
        ca, cb = curves_a.get(coord), curves_b.get(coord)
        entry: dict = {}
        if ca:
            entry["final_value_a"] = ca[-1]["value"]
        if cb:
            entry["final_value_b"] = cb[-1]["value"]
        if ca and cb:
            entry["final_value_delta"] = \
                entry["final_value_b"] - entry["final_value_a"]
            # The worse final value: the common target both runs reached.
            target = max(ca[-1]["value"], cb[-1]["value"])
            tta = time_to_target(ca, target)
            ttb = time_to_target(cb, target)
            entry["target_value"] = target
            entry["time_to_target_a"] = tta
            entry["time_to_target_b"] = ttb
            if tta and ttb and tta["seconds"] > 0:
                entry["time_to_target_ratio"] = \
                    ttb["seconds"] / max(tta["seconds"], 1e-9)
            entry["self_time_to_target_a"] = time_to_fraction(ca, fraction)
            entry["self_time_to_target_b"] = time_to_fraction(cb, fraction)
            entry["curve_a"] = ca
            entry["curve_b"] = cb
        coords[coord] = entry
    waves_a = fit_wave_summary(rows_a)
    waves_b = fit_wave_summary(rows_b)
    for coord in sorted(set(waves_a) | set(waves_b)):
        entry = coords.setdefault(coord, {})
        if coord in waves_a:
            entry["fit_waves_a"] = waves_a[coord]
        if coord in waves_b:
            entry["fit_waves_b"] = waves_b[coord]
    out["coordinates"] = coords
    out["final_metrics"] = {"a": final_validation_metrics(rows_a),
                            "b": final_validation_metrics(rows_b)}
    return out
