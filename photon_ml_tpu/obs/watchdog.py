"""Convergence watchdogs: loud, defined failure for sick training runs.

The ~90-minute flagship was a black box until it exited: a NaN'd
objective surfaced as a silent line-search stall, a diverging fit burned
its whole wall budget, and a straggling iteration looked like progress.
The watchdog sits on the same per-iteration telemetry stream the run
ledger records (obs/ledger.py) and turns those shapes into a LOUD event
plus a defined action — off by default at one ``None`` check per site
(the photon-fault discipline; ``obs.watchdog_config()`` is the switch).

Detectors (each independently armed by its config field):

* ``nan``        — NaN/Inf in an ACCEPTED objective value or gradient
  norm, or a line search that failed on non-finite probe values
  (transient non-finite PROBES are normal Armijo backtracking and are
  never flagged).
* ``stall``      — no objective improvement beyond ``stall_rtol`` for
  ``stall_iterations`` consecutive iterations.
* ``divergence`` — the objective exceeds the best seen by
  ``divergence_factor × max(|f0|, 1)``.
* ``slow_iter``  — one iteration's wall time exceeds
  ``iter_seconds_factor ×`` the EMA of previous iterations (needs ≥ 3
  observations before it can fire — compile-heavy first iterations are
  expected).
* ``gap``        — the duality-gap convergence gate of the stochastic
  streamed solvers (optim/stochastic.py): ``gap <= gap_tolerance``
  fires with ``gap_action`` (default ``stop`` — convergence certified,
  stop paying for epochs); a NON-FINITE gap is the NaN failure shape
  and fires the ``nan`` detector (default raise). Fed via
  :meth:`ConvergenceWatchdog.observe_gap`; batch L-BFGS never calls it,
  so arming ``gap=`` is a no-op there.

Every alert emits a ``WatchdogAlert`` event (→ a timeline instant + the
``photon_watchdog_alerts_total{kind=...}`` counter via the obs bridge)
and a ``watchdog`` ledger row, then applies the detector's ACTION:
``warn`` logs, ``stop`` asks the optimizer to stop early (a defined
degradation — the partial ledger and checkpoint survive), ``raise``
raises :class:`WatchdogError` (the defined error of the chaos drills —
chaos-testable by poisoning the objective through photon-fault's
``nan`` kind).
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Optional

logger = logging.getLogger("photon_ml_tpu.obs")

_ACTIONS = ("off", "warn", "stop", "raise")


class WatchdogError(RuntimeError):
    """A convergence watchdog fired with action="raise" — the DEFINED
    error of a sick training run (NaN objective, divergence)."""

    def __init__(self, kind: str, detail: str):
        super().__init__(f"watchdog[{kind}]: {detail}")
        self.kind = kind
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    """Which detectors are armed and what each does when it fires.
    The defaults arm ONLY the NaN detector — install via
    ``obs.set_watchdog(WatchdogConfig())`` / ``game_train --watchdog``;
    no config installed = every site pays one ``None`` check."""

    nan: str = "raise"
    stall_iterations: int = 0       # 0 = off
    stall_rtol: float = 1e-9
    stall_action: str = "stop"
    divergence_factor: float = 0.0  # 0 = off
    divergence_action: str = "raise"
    iter_seconds_factor: float = 0.0  # 0 = off
    iter_action: str = "warn"
    gap_tolerance: float = 0.0      # 0 = off (absolute duality gap)
    gap_action: str = "stop"

    def __post_init__(self):
        for field, value in (("nan", self.nan),
                             ("stall_action", self.stall_action),
                             ("divergence_action", self.divergence_action),
                             ("iter_action", self.iter_action),
                             ("gap_action", self.gap_action)):
            if value not in _ACTIONS:
                raise ValueError(f"watchdog {field} must be one of "
                                 f"{_ACTIONS}, got {value!r}")
        if self.stall_iterations < 0:
            raise ValueError("stall_iterations must be >= 0")
        if self.divergence_factor < 0 or self.iter_seconds_factor < 0:
            raise ValueError("watchdog factors must be >= 0")
        if self.gap_tolerance < 0:
            raise ValueError("gap_tolerance must be >= 0")


def parse_watchdog_config(spec: str) -> WatchdogConfig:
    """``key=value,...`` mini-DSL (``game_train --watchdog``): ``nan=``
    raise|warn|stop|off; ``stall=K[:action]`` (iterations); ``stall_rtol=``;
    ``divergence=F[:action]``; ``slow_iter=F[:action]``; ``gap=TOL[:action]``
    (absolute duality-gap convergence gate, stochastic solvers only). A bare
    ``--watchdog`` takes every default (NaN → raise)."""
    kv: dict[str, str] = {}
    for part in (p for p in spec.split(",") if p.strip()):
        k, sep, v = part.partition("=")
        if not sep:
            raise ValueError(f"watchdog spec needs key=value, got {part!r}")
        kv[k.strip()] = v.strip()
    known = {"nan", "stall", "stall_rtol", "divergence", "slow_iter", "gap"}
    unknown = set(kv) - known
    if unknown:
        raise ValueError(f"unknown watchdog keys {sorted(unknown)}; "
                         f"expected {sorted(known)}")

    def _split(value: str, default_action: str) -> tuple[str, str]:
        main, sep, action = value.partition(":")
        return main, (action if sep else default_action)

    d = WatchdogConfig()
    out = {"nan": kv.get("nan", d.nan)}
    if "stall" in kv:
        k, action = _split(kv["stall"], d.stall_action)
        out["stall_iterations"] = int(k)
        out["stall_action"] = action
    if "stall_rtol" in kv:
        out["stall_rtol"] = float(kv["stall_rtol"])
    if "divergence" in kv:
        f, action = _split(kv["divergence"], d.divergence_action)
        out["divergence_factor"] = float(f)
        out["divergence_action"] = action
    if "slow_iter" in kv:
        f, action = _split(kv["slow_iter"], d.iter_action)
        out["iter_seconds_factor"] = float(f)
        out["iter_action"] = action
    if "gap" in kv:
        f, action = _split(kv["gap"], d.gap_action)
        out["gap_tolerance"] = float(f)
        out["gap_action"] = action
    return WatchdogConfig(**out)


class ConvergenceWatchdog:
    """Per-optimization detector state. One instance per optimizer run
    (``minimize_streaming`` builds one when a config is installed);
    ``observe`` once per ACCEPTED iteration."""

    def __init__(self, config: WatchdogConfig,
                 coordinate: Optional[str] = None):
        self.config = config
        self.coordinate = coordinate
        self._f0: Optional[float] = None
        self._best: Optional[float] = None
        self._stall = 0
        self._ema: Optional[float] = None
        self._ema_n = 0

    # -- alert plumbing ------------------------------------------------------

    def _alert(self, kind: str, action: str, detail: str,
               **fields) -> Optional[str]:
        from photon_ml_tpu import obs
        from photon_ml_tpu.utils import events as ev_mod

        ev_mod.default_emitter.emit(ev_mod.WatchdogAlert(
            kind=kind, action=action, coordinate=self.coordinate,
            detail=detail))
        led = obs.ledger()
        if led is not None:
            led.record("watchdog", watchdog_kind=kind, action=action,
                       detail=detail, **fields)
            led.flush()  # the next thing may be a raise — keep the row
        if action == "warn":
            logger.warning("watchdog[%s]%s: %s", kind,
                           f" ({self.coordinate})" if self.coordinate
                           else "", detail)
            return None
        if action == "stop":
            logger.warning("watchdog[%s]%s: %s — stopping early", kind,
                           f" ({self.coordinate})" if self.coordinate
                           else "", detail)
            return "stop"
        raise WatchdogError(kind, detail)

    # -- detectors -----------------------------------------------------------

    def on_line_search_failure(self, last_probe_value: float,
                               iteration: int) -> Optional[str]:
        """A failed line search whose probes were NON-FINITE is the NaN
        failure shape (a poisoned objective NaNs every probe); a finite
        failed search is ordinary numerical exhaustion and stays the
        optimizer's own stop path."""
        if self.config.nan != "off" and \
                not math.isfinite(last_probe_value):
            return self._alert(
                "nan", self.config.nan,
                f"line search failed on a non-finite objective "
                f"(value={last_probe_value!r}) at iteration {iteration}",
                iteration=iteration, value=last_probe_value)
        return None

    def observe(self, iteration: int, value: float, grad_norm: float,
                seconds: float) -> Optional[str]:
        """Feed one accepted iteration; returns "stop" when an armed
        detector with action="stop" fired (the caller breaks its loop),
        None otherwise. action="raise" raises WatchdogError."""
        cfg = self.config
        if cfg.nan != "off" and (not math.isfinite(value)
                                 or not math.isfinite(grad_norm)):
            return self._alert(
                "nan", cfg.nan,
                f"non-finite convergence state at iteration {iteration} "
                f"(value={value!r}, grad_norm={grad_norm!r})",
                iteration=iteration, value=value, grad_norm=grad_norm)
        if self._f0 is None:
            self._f0 = value
        if cfg.divergence_factor > 0 and self._best is not None:
            limit = self._best + cfg.divergence_factor * \
                max(abs(self._f0), 1.0)
            if value > limit:
                return self._alert(
                    "divergence", cfg.divergence_action,
                    f"objective {value:.6g} exceeded best "
                    f"{self._best:.6g} by more than "
                    f"{cfg.divergence_factor:g} x max(|f0|, 1) at "
                    f"iteration {iteration}",
                    iteration=iteration, value=value, best=self._best)
        if cfg.stall_iterations > 0:
            if self._best is not None and value >= self._best - \
                    cfg.stall_rtol * max(abs(self._best), 1e-12):
                self._stall += 1
            else:
                self._stall = 0
            if self._stall >= cfg.stall_iterations:
                self._stall = 0
                return self._alert(
                    "stall", cfg.stall_action,
                    f"no objective progress beyond rtol "
                    f"{cfg.stall_rtol:g} for {cfg.stall_iterations} "
                    f"consecutive iterations (value {value:.6g})",
                    iteration=iteration, value=value)
        if self._best is None or value < self._best:
            self._best = value
        if cfg.iter_seconds_factor > 0:
            if self._ema_n >= 3 and seconds > \
                    cfg.iter_seconds_factor * self._ema:
                verdict = self._alert(
                    "slow_iter", cfg.iter_action,
                    f"iteration {iteration} took {seconds:.3g}s vs "
                    f"{self._ema:.3g}s EMA "
                    f"(> {cfg.iter_seconds_factor:g}x)",
                    iteration=iteration, seconds=seconds, ema=self._ema)
                if verdict is not None:
                    return verdict
            self._ema = (seconds if self._ema is None
                         else 0.7 * self._ema + 0.3 * seconds)
            self._ema_n += 1
        return None

    def observe_gap(self, iteration: int, gap: float) -> Optional[str]:
        """Feed the epoch's duality gap (stochastic solvers,
        optim/stochastic.py). A NON-FINITE gap is the NaN failure shape
        (a poisoned certificate must not silently certify convergence);
        ``gap <= gap_tolerance`` fires the ``gap`` detector — the
        default ``stop`` is the gap-gated convergence stop."""
        cfg = self.config
        if cfg.nan != "off" and not math.isfinite(gap):
            return self._alert(
                "nan", cfg.nan,
                f"non-finite duality gap at iteration {iteration} "
                f"(gap={gap!r})",
                iteration=iteration, gap=gap)
        if cfg.gap_tolerance > 0 and gap <= cfg.gap_tolerance:
            return self._alert(
                "gap", cfg.gap_action,
                f"duality gap {gap:.6g} <= tolerance "
                f"{cfg.gap_tolerance:g} at iteration {iteration} — "
                f"convergence certified",
                iteration=iteration, gap=gap)
        return None
