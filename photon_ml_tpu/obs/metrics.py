"""Process-wide metrics registry: counters, gauges, histograms.

Generalizes ``serving/metrics.py``'s scoreboard into one registry the
whole stack feeds (docs/OBSERVABILITY.md has the metric catalog):
training-side transfer accounting (``photon_transfer_bytes_total`` /
``photon_transfer_seconds_total`` from the ``device_put`` wrapper in
ops/streaming_sparse.py), compile-cache miss counts, the peak in-flight
chunk gauge (the n=100M enqueue-scratch failure mode, finally measurable),
and retry/straggler/recovery counters fed from the event stream by
``obs/bridge.py``. Exported as Prometheus text — the serving ``/metrics``
endpoint appends the active registry, and batch runs write the same text
via ``game_train --metrics-dump``.

All mutation is thread-safe: one registry lock guards metric CREATION,
one lock per metric guards its updates (the HTTP front end, the batcher
worker, and pipeline threads record concurrently).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

# Ring size for histogram reservoirs: large enough that p99 over recent
# observations is stable, small enough that percentile() stays trivial
# (shared with serving/metrics.py's latency reservoirs).
RING = 8192


class Histogram:
    """Percentiles over the most recent ``size`` observations.

    This IS serving's latency reservoir (serving/metrics.py re-exports it
    as ``LatencyHistogram``); ``observe`` is the registry-style alias of
    ``record``.
    """

    def __init__(self, size: int = RING):
        self._lock = threading.Lock()
        self._buf = np.zeros(size, np.float64)
        self._n = 0  # total ever recorded
        self._sum = 0.0

    def record(self, value: float) -> None:
        with self._lock:
            self._buf[self._n % self._buf.shape[0]] = value
            self._n += 1
            self._sum += value

    observe = record

    @property
    def count(self) -> int:
        return self._n

    def percentile(self, p: float) -> float:
        with self._lock:
            k = min(self._n, self._buf.shape[0])
            if k == 0:
                return 0.0
            return float(np.percentile(self._buf[:k], p))

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._n if self._n else 0.0

    def summary(self) -> dict:
        return {"count": self._n, "mean_ms": self.mean() * 1e3,
                "p50_ms": self.percentile(50) * 1e3,
                "p95_ms": self.percentile(95) * 1e3,
                "p99_ms": self.percentile(99) * 1e3}

    def values(self) -> dict:
        """Registry exposition: count/sum + quantiles in native units."""
        return {"count": self._n, "sum": self._sum,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}


class Counter:
    """Monotonic float counter."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counter increment must be >= 0, got {v}")
        with self._lock:
            self.value += v


class Gauge:
    """Set/inc/dec gauge that also tracks its high-water mark — the
    ``peak`` is what turns "enqueue scratch piled up" from a code comment
    into a testable number (ISSUE 7 satellite 1)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0
        self.peak = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v
            if v > self.peak:
                self.peak = v

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v
            if self.value > self.peak:
                self.peak = self.value

    def dec(self, v: float = 1.0) -> None:
        with self._lock:
            self.value -= v


def _key(name: str, labels: dict) -> tuple:
    return (name,) + tuple(sorted(labels.items()))


def _render_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class MetricsRegistry:
    """Name+labels → metric. One instance per process is the norm
    (``obs.enable()`` installs it; ``obs.metrics()`` hands it out behind
    the one-None-check discipline)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, object] = {}

    def _get(self, cls, name: str, labels: dict):
        key = _key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls()
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r}{labels} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    # -- views -------------------------------------------------------------

    def snapshot(self) -> dict:
        """Flat dict view: ``name{label="v"}`` → value(s)."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict[str, object] = {}
        for key, m in items:
            name, labels = key[0], key[1:]
            base = name + _render_labels(labels)
            if isinstance(m, Counter):
                out[base] = m.value
            elif isinstance(m, Gauge):
                out[base] = m.value
                out[name + "_peak" + _render_labels(labels)] = m.peak
            else:
                for k, v in m.values().items():
                    out[f"{name}_{k}" + _render_labels(labels)] = v
        return out

    def render_text(self) -> str:
        """Prometheus text exposition of every registered metric."""
        lines = []
        for k in sorted(self.snapshot().items()):
            name, v = k
            lines.append(f"{name} {v:.10g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self, path: str) -> None:
        import os

        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.render_text())
        os.replace(tmp, path)


def parse_prometheus_text(text: str) -> dict[str, float]:
    """Inverse of :meth:`MetricsRegistry.render_text` (also accepts the
    serving endpoint's body): ``name{labels}`` → float value. Comment
    and malformed lines are skipped — the parser reads dumps produced by
    THIS repo, but tolerates hand edits."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def metric_value(parsed: dict[str, float], name: str,
                 default: Optional[float] = None) -> Optional[float]:
    """Sum of ``name``'s series across label sets in a parsed dump (a
    bare counter matches itself; a labeled family sums its children)."""
    if name in parsed:
        return parsed[name]
    total = None
    for k, v in parsed.items():
        if k.startswith(name + "{"):
            total = (total or 0.0) + v
    return default if total is None else total
