"""Event→span bridge: lifecycle events become spans, counters, instants.

Every finally-guarded ``*Start``/``*Finish`` pair the repo already emits
(Training, Staging, StreamStage, Ingest, Scoring — utils/events.py)
becomes a span with ZERO call-site rewrites: the bridge is one listener
on the event emitter. Because events fire synchronously in the emitting
thread and the pairs are finally-guarded (PML007 enforces that), opening
the span on Start and closing it on Finish puts it exactly where a
hand-written ``with`` block would — including contextvar parenting, so
explicit spans opened INSIDE a lifecycle (chunk transfers during a
streamed fit) nest under it.

Non-pair events feed the metrics registry (retry/straggler/recovery
counters — the observability the hardening pass promised but never
measured) and drop instant markers on the timeline.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

from photon_ml_tpu.utils import events as ev_mod

logger = logging.getLogger("photon_ml_tpu.obs")

# *Start/*Finish pair prefix → the event field that keys concurrent
# scopes of the same kind (None: at most one open scope of that kind).
_PAIR_KEYS = {
    "Training": "task",
    "Staging": "label",
    "StreamStage": "shard_id",
    "Ingest": None,
    "Scoring": "source",
}

# Event class name → counter fed from the event stream.
_EVENT_COUNTERS = {
    "StagingRetry": "photon_staging_retries_total",
    "StagingStraggler": "photon_staging_stragglers_total",
    "CheckpointRecovered": "photon_checkpoint_recoveries_total",
    "BootRecovered": "photon_boot_recoveries_total",
    "IngestFallback": "photon_ingest_fallbacks_total",
}


class EventSpanBridge:
    """One emitter listener; register via :func:`install_bridge`."""

    def __init__(self, tracer=None, metrics=None):
        # None = resolve the active runtime object per event, so the
        # bridge keeps working across obs.enable()/disable() cycles.
        self._tracer = tracer
        self._metrics = metrics
        self._open: dict[tuple, object] = {}
        self.opened = 0
        self.closed = 0

    def _active(self):
        from photon_ml_tpu import obs

        return (self._tracer if self._tracer is not None else obs.tracer(),
                self._metrics if self._metrics is not None
                else obs.metrics())

    def stats(self) -> dict:
        return {"bridge_spans_opened": self.opened,
                "bridge_spans_closed": self.closed,
                "bridge_spans_leaked": len(self._open)}

    def __call__(self, event: ev_mod.Event) -> None:
        tracer, metrics = self._active()
        if tracer is None and metrics is None:
            return
        name = type(event).__name__
        args = dataclasses.asdict(event)
        if name.endswith("Start"):
            self._on_start(tracer, name[:-5], args)
        elif name.endswith("Finish"):
            self._on_finish(name[:-6], args)
        else:
            self._on_point(tracer, metrics, name, args)

    # -- pair handling -----------------------------------------------------

    def _scope_key(self, kind: str, args: dict) -> tuple:
        field = _PAIR_KEYS.get(kind)
        return (kind, args.get(field) if field else None)

    def _on_start(self, tracer, kind: str, args: dict) -> None:
        if tracer is None:
            return
        key = self._scope_key(kind, args)
        if key in self._open:
            # A Start with its predecessor still open means a leaked
            # scope upstream (PML007 territory) — close the stale one so
            # the trace shows two bounded spans, not one covering both.
            logger.warning("bridge: %s scope %r reopened while open — "
                           "closing the stale span", kind, key[1])
            self._end(key, {"stale": True})
        # The bridge is the sanctioned raw-pair user: open and close
        # arrive as separate event callbacks (PML009's cross-method
        # case), pairing delegated to the PML007-enforced finally
        # guards at the emit sites.
        self._open[key] = tracer.start(
            f"{_snake(kind)}", cat="lifecycle", **args)
        self.opened += 1

    def _on_finish(self, kind: str, args: dict) -> None:
        self._end(self._scope_key(kind, args), args)

    def _end(self, key: tuple, args: dict) -> None:
        span = self._open.pop(key, None)
        if span is None:
            return  # Finish without Start (bridge installed mid-scope)
        span.end(**args)
        self.closed += 1

    def close_all(self) -> None:
        """Close anything still open (driver shutdown path) so the dumped
        trace never contains phantom open lifecycle spans."""
        for key in list(self._open):
            self._end(key, {"closed_at_shutdown": True})

    # -- point events ------------------------------------------------------

    def _on_point(self, tracer, metrics, name: str, args: dict) -> None:
        if metrics is not None:
            counter = _EVENT_COUNTERS.get(name)
            if counter is not None:
                metrics.counter(counter).inc()
            elif name == "StagingShard":
                metrics.counter("photon_staging_shards_total",
                                source=str(args.get("source"))).inc()
            elif name == "IngestBlock":
                metrics.counter("photon_ingest_chunks_total",
                                source=str(args.get("source"))).inc()
                metrics.counter("photon_ingest_records_total").inc(
                    float(args.get("records") or 0))
            elif name == "WatchdogAlert":
                metrics.counter("photon_watchdog_alerts_total",
                                kind=str(args.get("kind"))).inc()
            elif name == "KernelFallback":
                metrics.counter("photon_kernel_fallbacks_total",
                                kernel=str(args.get("kernel"))).inc()
            elif name == "CoordinateUpdate":
                metrics.histogram(
                    "photon_coordinate_update_seconds").observe(
                        float(args.get("train_seconds") or 0.0))
            elif name == "ScoringBatch":
                metrics.counter("photon_scoring_rows_total").inc(
                    float(args.get("rows") or 0))
        if tracer is not None and name != "ScoringBatch":
            # ScoringBatch is per-flush in serving — too hot for a
            # timeline marker; its volume lives in the counter above.
            args.pop("validation", None)  # free-form dict, not trace args
            tracer.instant(_snake(name), cat="event", **args)


def _snake(name: str) -> str:
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i and not name[i - 1].isupper():
            out.append("_")
        out.append(c.lower())
    return "".join(out)


_INSTALLED: Optional[EventSpanBridge] = None


def install_bridge(emitter: Optional[ev_mod.EventEmitter] = None
                   ) -> EventSpanBridge:
    """Register the bridge on ``emitter`` (default: the process-wide
    default emitter). Idempotent: one bridge per process."""
    global _INSTALLED
    if _INSTALLED is None:
        _INSTALLED = EventSpanBridge()
        (emitter or ev_mod.default_emitter).register(_INSTALLED)
    return _INSTALLED


def uninstall_bridge(emitter: Optional[ev_mod.EventEmitter] = None) -> None:
    global _INSTALLED
    if _INSTALLED is not None:
        _INSTALLED.close_all()
        try:
            (emitter or ev_mod.default_emitter).unregister(_INSTALLED)
        except ValueError:
            pass  # already detached (e.g. a listener failure)
        _INSTALLED = None


def installed_bridge() -> Optional[EventSpanBridge]:
    return _INSTALLED
