"""Hyperparameter searchers: random and Bayesian (GP + EI).

Reference parity: photon-lib ``hyperparameter/search/RandomSearch.scala``
and ``GaussianProcessSearch.scala``: iteratively propose a config vector,
evaluate it via an :class:`EvaluationFunction`, and (for GP search) refit
the response surface and maximize expected improvement over a random
candidate pool. ``find_with_priors`` seeds the searcher with observations
from earlier runs (the reference's ``findWithPriors`` warm-start path).

Convention: MINIMIZE. Evaluation functions must negate reward metrics
(AUC, precision@k) — :mod:`photon_ml_tpu.hyperparameter.evaluation` does
this automatically from the evaluator's metric direction.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Callable, Optional, Sequence

import numpy as np

from photon_ml_tpu.hyperparameter import criteria
from photon_ml_tpu.hyperparameter.gp import fit_gp_with_kernel_search
from photon_ml_tpu.hyperparameter.kernels import Matern52, StationaryKernel
from photon_ml_tpu.utils.ranges import DoubleRange

logger = logging.getLogger("photon_ml_tpu.hyperparameter")


@dataclasses.dataclass(frozen=True)
class SearchDimension:
    """One searched variable: an inclusive range, optionally log-scaled
    (log10 — regularization weights search in log space)."""

    name: str
    range: DoubleRange
    log_scale: bool = True

    def to_unit(self, x):
        # Clip into the range first: prior observations may carry values
        # outside it (e.g. reg_weight 0.0 from an unregularized sweep),
        # which would otherwise produce log10(0) = -inf and poison the GP.
        x = self.range.clip(x)
        r = (self.range.transform(np.log10) if self.log_scale
             else self.range)
        return r.normalize(np.log10(x) if self.log_scale else x)

    def from_unit(self, u):
        r = (self.range.transform(np.log10) if self.log_scale
             else self.range)
        v = r.denormalize(np.clip(u, 0.0, 1.0))
        return np.power(10.0, v) if self.log_scale else v


@dataclasses.dataclass
class Observation:
    point: np.ndarray   # raw (un-normalized) config vector
    value: float        # minimized objective


@dataclasses.dataclass
class SearchResult:
    best_point: np.ndarray
    best_value: float
    observations: list[Observation]

    def best_config(self, dims: Sequence[SearchDimension]) -> dict:
        return {d.name: float(x) for d, x in zip(dims, self.best_point)}


class RandomSearch:
    """Uniform (log-uniform per dimension) random search.

    Reference: hyperparameter/search/RandomSearch.scala. Draws are Sobol'
    in the reference; seeded uniform draws here — the consumers only rely
    on coverage of the unit cube.
    """

    def __init__(self, dimensions: Sequence[SearchDimension],
                 evaluation_function: Callable[[np.ndarray], float],
                 seed: int = 1):
        self.dimensions = list(dimensions)
        self.evaluate = evaluation_function
        self._rng = np.random.default_rng(seed)
        self.observations: list[Observation] = []
        # Expected improvement of the LAST proposal (GP search sets it;
        # random/seed proposals have none) — rides into the run ledger's
        # per-trial rows so tuning runs are diffable (ISSUE 9).
        self._last_ei: Optional[float] = None

    def _draw(self) -> np.ndarray:
        u = self._rng.uniform(size=len(self.dimensions))
        return np.array([d.from_unit(ui)
                         for d, ui in zip(self.dimensions, u)])

    def _propose(self) -> np.ndarray:
        return self._draw()

    def find(self, n: int) -> SearchResult:
        from photon_ml_tpu import obs

        led = obs.ledger()
        for i in range(n):
            self._last_ei = None
            point = self._propose()
            bound = (led.bound(trial=i + 1) if led is not None
                     else contextlib.nullcontext())
            t0 = time.perf_counter()
            with bound:
                value = float(self.evaluate(point))
            self.observations.append(Observation(point, value))
            if led is not None:
                # One row per trial: the sampled config, the proposal's
                # expected improvement, the validation objective, and
                # the trial's wall seconds — `photon-obs diff` then
                # compares tuning runs like any other run.
                led.record(
                    "tuning_trial", trial=i + 1,
                    point={d.name: float(p)
                           for d, p in zip(self.dimensions, point)},
                    expected_improvement=self._last_ei,
                    objective=value,
                    seconds=round(time.perf_counter() - t0, 6))
            logger.info("hyperparameter trial %d/%d: %s -> %.6g",
                        i + 1, n,
                        {d.name: float(p) for d, p in
                         zip(self.dimensions, point)}, value)
        best = min(self.observations, key=lambda o: o.value)
        return SearchResult(best.point, best.value, list(self.observations))

    def find_with_priors(self, n: int,
                         priors: Sequence[Observation]) -> SearchResult:
        """Seed with prior observations then continue (reference:
        findWithPriors — reuse evaluations from previous runs)."""
        self.observations.extend(priors)
        return self.find(n)


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: GP response surface + expected improvement.

    Reference: hyperparameter/search/GaussianProcessSearch.scala. The first
    ``num_seed_points`` proposals are random; afterwards each proposal
    maximizes EI over a fresh random candidate pool under a GP refit to all
    observations (kernel params re-selected by marginal likelihood).
    """

    def __init__(self, dimensions: Sequence[SearchDimension],
                 evaluation_function: Callable[[np.ndarray], float],
                 seed: int = 1,
                 kernel: Optional[StationaryKernel] = None,
                 num_seed_points: int = 3,
                 num_candidates: int = 512):
        super().__init__(dimensions, evaluation_function, seed)
        self.kernel = kernel if kernel is not None else Matern52()
        self.num_seed_points = num_seed_points
        self.num_candidates = num_candidates

    def _to_unit_matrix(self, points: np.ndarray) -> np.ndarray:
        cols = [d.to_unit(points[:, j])
                for j, d in enumerate(self.dimensions)]
        return np.stack(cols, axis=1)

    def _propose(self) -> np.ndarray:
        if len(self.observations) < self.num_seed_points:
            return self._draw()
        pts = np.stack([o.point for o in self.observations])
        vals = np.array([o.value for o in self.observations])
        x = self._to_unit_matrix(pts)
        model = fit_gp_with_kernel_search(self.kernel, x, vals, self._rng)
        cand_u = self._rng.uniform(
            size=(self.num_candidates, len(self.dimensions)))
        mean, std = model.predict(cand_u)
        ei = criteria.expected_improvement(mean, std, float(vals.min()))
        u = cand_u[int(np.argmax(ei))]
        self._last_ei = float(np.max(ei))
        return np.array([d.from_unit(ui)
                         for d, ui in zip(self.dimensions, u)])
