"""Hyperparameter tuning: random search and Bayesian (GP + EI) search.

Reference parity: photon-lib ``hyperparameter/`` — ``search/RandomSearch``,
``search/GaussianProcessSearch``, ``estimators/GaussianProcessEstimator``
with Matern52/RBF kernels, ``criteria/ExpectedImprovement``, and
``EvaluationFunction`` — the inner loop of GameTrainingDriver's
``hyperParameterTuning`` mode.
"""

from photon_ml_tpu.hyperparameter.criteria import (  # noqa: F401
    expected_improvement, lower_confidence_bound)
from photon_ml_tpu.hyperparameter.evaluation import (  # noqa: F401
    GameEvaluationFunction)
from photon_ml_tpu.hyperparameter.gp import (  # noqa: F401
    GaussianProcessModel, fit_gp, fit_gp_with_kernel_search)
from photon_ml_tpu.hyperparameter.kernels import (  # noqa: F401
    RBF, Matern52, StationaryKernel, get_kernel)
from photon_ml_tpu.hyperparameter.search import (  # noqa: F401
    GaussianProcessSearch, Observation, RandomSearch, SearchDimension,
    SearchResult)
