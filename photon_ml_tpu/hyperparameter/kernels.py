"""Stationary covariance kernels for the Gaussian-process tuner.

Reference parity: photon-lib ``hyperparameter/estimators/kernels/`` —
``RBF.scala``, ``Matern52.scala``, ``StationaryKernel.scala``. Host-side
numpy: kernel algebra runs on a handful of observed configs (tens of
points), never on device.

Both kernels support per-dimension lengthscales (ARD) and an amplitude;
inputs are expected pre-normalized to [0, 1]^d by the search driver.
"""

from __future__ import annotations

import dataclasses

import numpy as np

_SQRT5 = np.sqrt(5.0)


def _scaled_sqdist(x1: np.ndarray, x2: np.ndarray,
                   lengthscale: np.ndarray) -> np.ndarray:
    """Pairwise squared distance after per-dimension lengthscale division."""
    a = x1 / lengthscale
    b = x2 / lengthscale
    d2 = (np.sum(a * a, axis=1)[:, None] + np.sum(b * b, axis=1)[None, :]
          - 2.0 * a @ b.T)
    return np.maximum(d2, 0.0)


@dataclasses.dataclass(frozen=True)
class StationaryKernel:
    """amplitude² · k(r/lengthscale) with optional observation noise."""

    amplitude: float = 1.0
    lengthscale: np.ndarray | float = 1.0
    noise: float = 1e-4

    def _ls(self, dim: int) -> np.ndarray:
        ls = np.asarray(self.lengthscale, dtype=np.float64)
        if ls.ndim == 0:
            ls = np.full(dim, float(ls))
        return ls

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def with_params(self, amplitude: float, lengthscale,
                    noise: float) -> "StationaryKernel":
        return dataclasses.replace(self, amplitude=amplitude,
                                   lengthscale=lengthscale, noise=noise)


@dataclasses.dataclass(frozen=True)
class RBF(StationaryKernel):
    """Squared-exponential kernel (reference: kernels/RBF.scala)."""

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        d2 = _scaled_sqdist(x1, x2, self._ls(x1.shape[1]))
        return self.amplitude ** 2 * np.exp(-0.5 * d2)


@dataclasses.dataclass(frozen=True)
class Matern52(StationaryKernel):
    """Matérn ν=5/2 kernel (reference: kernels/Matern52.scala) — the
    reference's default for hyperparameter response surfaces (twice
    differentiable but heavier-tailed than RBF)."""

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        d = np.sqrt(_scaled_sqdist(x1, x2, self._ls(x1.shape[1])))
        s = _SQRT5 * d
        return self.amplitude ** 2 * (1.0 + s + s * s / 3.0) * np.exp(-s)


KERNELS = {"rbf": RBF, "matern52": Matern52}


def get_kernel(name: str, **kw) -> StationaryKernel:
    try:
        return KERNELS[name.lower()](**kw)
    except KeyError:
        raise ValueError(
            f"unknown kernel {name!r}; have {sorted(KERNELS)}") from None
