"""Gaussian-process regression over observed hyperparameter evaluations.

Reference parity: photon-lib ``hyperparameter/estimators/
GaussianProcessEstimator.scala`` / ``GaussianProcessModel.scala`` — fit a GP
to (config, loss) observations, predict posterior mean/std at candidate
configs. Kernel hyperparameters are chosen by maximizing the log marginal
likelihood over a random sample of kernel configurations (the reference
samples kernel parameters rather than running gradient ascent).

Host-side numpy/scipy: the GP sees tens of points; this is driver control
logic, not device compute.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy import linalg

from photon_ml_tpu.hyperparameter.kernels import StationaryKernel


@dataclasses.dataclass
class GaussianProcessModel:
    """Posterior GP: stores the Cholesky factor of K(X,X)+σ²I."""

    kernel: StationaryKernel
    x_train: np.ndarray        # (n, d), normalized to [0,1]^d
    y_mean: float              # subtracted target mean
    _chol: np.ndarray
    _alpha: np.ndarray         # K⁻¹ (y - mean)

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior mean and std at candidate points ``x`` (m, d)."""
        k_star = self.kernel(self.x_train, x)            # (n, m)
        mean = self.y_mean + k_star.T @ self._alpha
        v = linalg.solve_triangular(self._chol, k_star, lower=True)
        # Stationary kernels have k(x,x) = amplitude² on the diagonal.
        prior = self.kernel.amplitude ** 2
        var = np.maximum(prior - np.sum(v * v, axis=0), 1e-12)
        return mean, np.sqrt(var)

    def log_marginal_likelihood(self, y: np.ndarray) -> float:
        n = len(y)
        resid = y - self.y_mean
        return float(-0.5 * resid @ self._alpha
                     - np.sum(np.log(np.diagonal(self._chol)))
                     - 0.5 * n * np.log(2.0 * np.pi))


def fit_gp(kernel: StationaryKernel, x: np.ndarray,
           y: np.ndarray) -> GaussianProcessModel:
    """Exact GP fit via Cholesky with jitter escalation."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    y_mean = float(y.mean()) if len(y) else 0.0
    K = kernel(x, x)
    jitter = kernel.noise
    for _ in range(8):
        try:
            chol = linalg.cholesky(K + jitter * np.eye(len(x)), lower=True)
            break
        except linalg.LinAlgError:
            jitter *= 10.0
    else:  # pragma: no cover - pathological conditioning
        raise linalg.LinAlgError("GP covariance not positive definite")
    alpha = linalg.cho_solve((chol, True), y - y_mean)
    return GaussianProcessModel(kernel=kernel, x_train=x, y_mean=y_mean,
                                _chol=chol, _alpha=alpha)


def fit_gp_with_kernel_search(
    base_kernel: StationaryKernel,
    x: np.ndarray,
    y: np.ndarray,
    rng: np.random.Generator,
    num_kernel_samples: int = 32,
) -> GaussianProcessModel:
    """Pick kernel params by max log-marginal-likelihood over random draws.

    Mirrors the reference estimator's kernel-parameter sampling: amplitude
    is anchored to the target std, per-dimension lengthscales drawn
    log-uniform in [0.05, 2] (inputs are normalized to the unit cube).
    """
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    d = x.shape[1]
    y_std = float(y.std()) or 1.0
    best_model, best_lml = None, -np.inf
    for i in range(num_kernel_samples):
        if i == 0:
            amp, ls = y_std, np.full(d, 0.5)
        else:
            amp = y_std * float(np.exp(rng.uniform(np.log(0.3), np.log(3.0))))
            ls = np.exp(rng.uniform(np.log(0.05), np.log(2.0), size=d))
        k = base_kernel.with_params(amp, ls, base_kernel.noise)
        try:
            model = fit_gp(k, x, y)
        except linalg.LinAlgError:  # pragma: no cover
            continue
        lml = model.log_marginal_likelihood(y)
        if lml > best_lml:
            best_model, best_lml = model, lml
    assert best_model is not None
    return best_model
