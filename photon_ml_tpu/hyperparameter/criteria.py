"""Acquisition criteria for Bayesian hyperparameter search.

Reference parity: photon-lib ``hyperparameter/criteria/
ExpectedImprovement.scala`` (+ ConfidenceBound). Convention: the searcher
MINIMIZES — evaluation functions negate reward metrics such as AUC.
"""

from __future__ import annotations

import numpy as np
from scipy import stats


def expected_improvement(mean: np.ndarray, std: np.ndarray,
                         best: float) -> np.ndarray:
    """EI for minimization: E[max(best - f, 0)] under N(mean, std²)."""
    std = np.maximum(std, 1e-12)
    z = (best - mean) / std
    return (best - mean) * stats.norm.cdf(z) + std * stats.norm.pdf(z)


def lower_confidence_bound(mean: np.ndarray, std: np.ndarray,
                           kappa: float = 2.0) -> np.ndarray:
    """LCB acquisition (higher is better for minimization): -(μ - κσ)."""
    return -(mean - kappa * std)
