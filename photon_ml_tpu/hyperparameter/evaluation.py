"""Evaluation functions bridging the searchers to GAME training.

Reference parity: photon-lib ``hyperparameter/EvaluationFunction.scala`` and
the GameEstimator glue in GameTrainingDriver's hyperparameter-tuning mode:
a config vector (one regularization weight per tunable coordinate, searched
in log space) → train a GAME model → validation metric. Reward metrics
(AUC, precision@k) are negated so every searcher minimizes.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation.evaluators import EvaluatorType, MetricDirection
from photon_ml_tpu.hyperparameter.search import (Observation,
                                                 SearchDimension)
from photon_ml_tpu.utils.ranges import DoubleRange

logger = logging.getLogger("photon_ml_tpu.hyperparameter")


@dataclasses.dataclass
class GameEvaluationFunction:
    """Vector of per-coordinate reg weights → validation objective.

    ``estimator`` is a ``GameEstimator``; ``coordinate_ids`` names the
    coordinates whose regularization weight is being tuned (the searched
    vector is ordered the same way). The estimator's grids are bypassed:
    each trial fits exactly one configuration.
    """

    estimator: "GameEstimator"  # noqa: F821 - avoid circular import
    data: object                # GameDataset
    validation_data: object     # GameDataset
    coordinate_ids: Sequence[str]
    reg_weight_range: DoubleRange = DoubleRange(1e-4, 1e4)
    # Warm starts / partial retraining carried into every trial — without
    # these a tuned run would silently retrain locked coordinates.
    initial_models: Optional[dict] = None
    locked_coordinates: Optional[set] = None
    # Best trial seen: (objective, point, results) — lets the driver reuse
    # the winning trial's already-trained model instead of refitting.
    _best: Optional[tuple] = dataclasses.field(
        default=None, init=False, repr=False, compare=False)

    def dimensions(self) -> list[SearchDimension]:
        return [SearchDimension(cid, self.reg_weight_range, log_scale=True)
                for cid in self.coordinate_ids]

    def _sign(self) -> float:
        primary = EvaluatorType.parse(
            self.estimator.validation_evaluators[0])
        return (-1.0 if primary.direction == MetricDirection.HIGHER_IS_BETTER
                else 1.0)

    def __call__(self, point: np.ndarray) -> float:
        est = self._with_weights(point)
        results = est.fit(self.data, self.validation_data,
                          initial_models=self.initial_models,
                          locked_coordinates=self.locked_coordinates)
        assert len(results) == 1, "tuning trials must fit one config"
        evaluation = results[0].evaluation
        assert evaluation is not None, "tuning requires validation evaluators"
        value = self._sign() * float(evaluation.primary_value)
        if self._best is None or value < self._best[0]:
            self._best = (value, np.array(point), results)
        return value

    def best_trial(self) -> Optional[tuple]:
        """(objective, point, results) of the best trial this function has
        evaluated, or None if never called."""
        return self._best

    def _with_weights(self, point: np.ndarray):
        import copy

        est = copy.copy(self.estimator)
        weights = dict(zip(self.coordinate_ids, point))
        coords = {}
        for cid, cc in est.coordinate_configs.items():
            opt = cc.optimization
            if cid in weights:
                reg = dataclasses.replace(opt.regularization,
                                          reg_weight=float(weights[cid]))
                opt = dataclasses.replace(opt, regularization=reg)
            # Grids cleared on EVERY coordinate: each trial fits one config.
            coords[cid] = dataclasses.replace(cc, optimization=opt,
                                              reg_weight_grid=())
        est.coordinate_configs = coords
        return est

    def observations_from_results(self, results) -> list[Observation]:
        """Convert prior GameResults (e.g. the initial grid sweep) into
        seed observations (reference: findWithPriors' prior data)."""
        sign = self._sign()
        obs = []
        for r in results:
            if r.evaluation is None:
                continue
            point = np.array([
                r.configs[cid].regularization.reg_weight
                for cid in self.coordinate_ids])
            obs.append(Observation(point, sign * float(
                r.evaluation.primary_value)))
        return obs
