"""GAME mixed-effects walkthrough (MovieLens-style): a global fixed-effect
coordinate plus a per-user random-effect coordinate, trained by block
coordinate descent with a regularization grid, validated with grouped AUC,
checkpointed, and scored.

Run: python examples/game_mixed_effects.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)
import tempfile

import numpy as np

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FixedEffectDataConfiguration,
                                       RandomEffectDataConfiguration)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.api.transformer import GameTransformer
from photon_ml_tpu.data import synthetic
from photon_ml_tpu.data.game_data import from_synthetic
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType


def main():
    rng = np.random.default_rng(0)
    n = 6000
    ds = from_synthetic(synthetic.game_data(
        rng, n=n, d_global=16, re_specs={"userId": (100, 6)}))
    idx = rng.permutation(n)
    train, val = ds.subset(idx[:int(0.8 * n)]), ds.subset(idx[int(0.8 * n):])

    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=60, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))
    estimator = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "fixed": CoordinateConfiguration(
                data=FixedEffectDataConfiguration("global"),
                optimization=opt, reg_weight_grid=(0.1, 1.0, 10.0)),
            "per-user": CoordinateConfiguration(
                data=RandomEffectDataConfiguration(
                    "userId", "re_userId", active_data_lower_bound=2),
                optimization=opt),
        },
        update_sequence=["fixed", "per-user"],
        mesh=make_mesh(),
        descent_iterations=2,
        validation_evaluators=["AUC", "AUC@userId"])

    with tempfile.TemporaryDirectory() as tmp:
        # Checkpoints under tmp/ck: kill this run mid-descent and re-running
        # the same fit resumes instead of restarting (cli: --resume).
        results = estimator.fit(train, validation_data=val,
                                checkpoint_dir=f"{tmp}/ck")
        best = estimator.select_best_model(results)
        print("grid results:")
        for r in results:
            reg = r.configs["fixed"].regularization.reg_weight
            print(f"  reg={reg:8.1f}  "
                  f"AUC={r.evaluation.metrics['AUC']:.3f}  "
                  f"per-user AUC={r.evaluation.metrics['AUC@userId']:.3f}")
        print(f"best: AUC={best.evaluation.metrics['AUC']:.3f}")

        scored = GameTransformer(best.model, ["AUC"])
        _, evaluation = scored.transform_and_evaluate(val)
        print(f"transformer AUC on validation: "
              f"{evaluation.metrics['AUC']:.3f}")


if __name__ == "__main__":
    main()
