"""Fixed-effect GLM quickstart: synthetic LIBSVM-style data → logistic
regression with L-BFGS + L2 over the device mesh → evaluate → save/load.

Run: python examples/glm_quickstart.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)
import tempfile

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.evaluation import evaluators as ev
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel import problem as dist_problem
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType


def main():
    rng = np.random.default_rng(0)
    n, d = 5000, 32
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[:, -1] = 1.0  # intercept column
    w_true = rng.normal(size=d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-X @ w_true))).astype(
        np.float32)

    mesh = make_mesh()  # (data, model) axes over all visible devices
    config = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=100, tolerance=1e-8),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))

    coef, result = dist_problem.run(
        losses.LOGISTIC, LabeledBatch.build(X, y), mesh, config,
        intercept_index=d - 1)
    print(f"converged={bool(result.converged)} "
          f"iterations={int(result.iterations)}")

    model = GeneralizedLinearModel(
        task=TaskType.LOGISTIC_REGRESSION,
        coefficients=Coefficients(coef.means))
    auc = float(ev.evaluate(ev.EvaluatorType.parse("AUC"),
                            model.compute_score(jnp.asarray(X)),
                            jnp.asarray(y)))
    print(f"train AUC: {auc:.3f}")

    with tempfile.TemporaryDirectory() as tmp:
        model_io.save_glm(model, f"{tmp}/model")
        back = model_io.load_glm(f"{tmp}/model")
        assert np.allclose(back.coefficients.means, coef.means)
    print("save/load round trip ok")


if __name__ == "__main__":
    main()
