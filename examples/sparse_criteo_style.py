"""Criteo-style sparse GAME fit: a 100k-feature ELL sparse fixed-effect
coordinate, optionally sharding the coefficient dimension over the mesh's
``model`` axis (BASELINE config 5 at example scale).

Run: python examples/sparse_criteo_style.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)
import numpy as np

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FixedEffectDataConfiguration)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.data import sparse
from photon_ml_tpu.data.game_data import from_sparse_batch
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType


def main():
    batch, _ = sparse.synthetic_sparse(
        n=50_000, num_features=100_000, nnz_per_row=32, seed=0)
    ds = from_sparse_batch(batch)  # one sparse "global" shard

    estimator = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "ctr": CoordinateConfiguration(
                # feature_sharded=True splits the 100k coefficients over
                # the mesh's model axis; margins psum over it, gradients
                # stay fully sharded (see parallel/sparse_objective.py).
                data=FixedEffectDataConfiguration(
                    "global", feature_sharded=True),
                optimization=GLMOptimizationConfiguration(
                    optimizer=OptimizerConfig(max_iterations=60,
                                              tolerance=1e-7),
                    regularization=RegularizationContext(
                        RegularizationType.L2, 1.0))),
        },
        update_sequence=["ctr"],
        mesh=make_mesh(),
        validation_evaluators=["AUC"])

    results = estimator.fit(ds, validation_data=ds)
    print(f"sparse CTR fit AUC: "
          f"{results[0].evaluation.metrics['AUC']:.3f}")
    w = np.asarray(results[0].model.models["ctr"].coefficients.means)
    print(f"coefficients: shape={w.shape} nonzero≈{(np.abs(w) > 1e-4).sum()}")


if __name__ == "__main__":
    main()
