"""Shared example bootstrap: make the repo root importable so the examples
run as plain scripts (``python examples/<name>.py``) without installing
the package. A script's own directory is always on sys.path, so a bare
``import _bootstrap`` works from any cwd."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
