"""The reference's full Avro pipeline, end to end.

Mirrors photon-client's production flow: daily-partitioned
TrainingExampleAvro input → feature maps built from the data → GAME fit →
a self-contained BayesianLinearModelAvro model directory (model + index
maps + entity vocabularies) → scoring NEW Avro data (with never-seen
entities) through those artifacts alone.

Everything runs through the real CLI drivers; ingestion uses the native
C++ Avro block decoder when a toolchain is available.

Run: python examples/avro_pipeline.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)
import os
import tempfile

import numpy as np

from photon_ml_tpu.avro import schemas
from photon_ml_tpu.avro.container import write_records
from photon_ml_tpu.cli import game_score, game_train


def make_records(rng, n, user_effects, user_base="u"):
    """Labels carry a REAL per-user effect (user_effects[uid] added to the
    margin) so the random-effect coordinate has signal to learn — and so
    unseen users at scoring time visibly lose that signal."""
    recs = []
    for _ in range(n):
        uid = int(rng.integers(0, len(user_effects)))
        feats = [{"name": f"x{j}", "term": "", "value": float(rng.normal())}
                 for j in range(6)]
        margin = (feats[0]["value"] + feats[1]["value"] - feats[2]["value"]
                  + user_effects[uid])
        recs.append({
            "label": float(rng.uniform() < 1 / (1 + np.exp(-margin))),
            "features": feats,
            "metadataMap": {"userId": f"{user_base}{uid}"},
        })
    return recs


def main():
    rng = np.random.default_rng(0)
    # Strong planted per-user effects; "new" users get their own (never
    # observed in training, so only the fixed effect can score them).
    seen_fx = rng.normal(scale=2.0, size=25)
    new_fx = rng.normal(scale=2.0, size=25)
    with tempfile.TemporaryDirectory() as td:
        # Daily-partitioned training data (three days).
        for day in ("2026/07/01", "2026/07/02", "2026/07/03"):
            os.makedirs(f"{td}/daily/{day}")
            write_records(f"{td}/daily/{day}/part-0.avro",
                          schemas.TRAINING_EXAMPLE_AVRO,
                          make_records(rng, 1500, seen_fx))
        write_records(f"{td}/val.avro", schemas.TRAINING_EXAMPLE_AVRO,
                      make_records(rng, 1000, seen_fx))
        # Scoring data: half the users were never seen in training — they
        # score with the fixed effect only (reference semantics).
        write_records(f"{td}/score.avro", schemas.TRAINING_EXAMPLE_AVRO,
                      make_records(rng, 500, seen_fx)
                      + make_records(rng, 500, new_fx, user_base="new"))

        summary = game_train.run(game_train.build_parser().parse_args([
            "--train", f"{td}/daily", "--validation", f"{td}/val.avro",
            "--date-range", "20260701-20260703",
            "--avro-feature-shard",
            "name=global,bags=features,intercept=true",
            "--avro-re-types", "userId",
            "--coordinate", "name=fixed,type=fixed,shard=global",
            "--coordinate",
            "name=per-user,type=random,shard=global,re=userId",
            "--update-sequence", "fixed,per-user",
            "--iterations", "2", "--evaluators", "AUC",
            "--opt-config", "fixed:optimizer=LBFGS,reg=L2,reg_weight=1.0",
            "--opt-config",
            "per-user:optimizer=LBFGS,reg=L2,reg_weight=5.0",
            "--model-output-format", "AVRO",
            "--output-dir", f"{td}/out",
        ]))
        print(f"validation AUC: {summary['best_metrics']['AUC']:.4f}")

        scored = game_score.run(game_score.build_parser().parse_args([
            "--data", f"{td}/score.avro",
            "--model-dir", f"{td}/out/best-avro",
            "--model-format", "AVRO",
            "--avro-feature-shard",
            "name=global,bags=features,intercept=true",
            "--avro-re-types", "userId",
            "--feature-index-dir", f"{td}/out/best-avro/index-maps",
            "--output-dir", f"{td}/scored",
            "--output-format", "BOTH",
            "--evaluators", "AUC",
        ]))
        print(f"scored {scored['num_rows']} rows "
              f"(half with unseen users), AUC {scored['metrics']['AUC']:.4f}")
        print("outputs:", sorted(os.listdir(f"{td}/scored")))

        # The unseen-entity contrast, made visible: seen users keep their
        # learned per-user effects; unseen ones fall back to the fixed
        # effect alone and lose that accuracy.
        import jax.numpy as jnp

        from photon_ml_tpu.evaluation.evaluators import auc

        npz = np.load(f"{td}/scored/scores.npz")
        seen_auc = float(auc(jnp.asarray(npz["score"][:500]),
                             jnp.asarray(npz["label"][:500])))
        unseen_auc = float(auc(jnp.asarray(npz["score"][500:]),
                               jnp.asarray(npz["label"][500:])))
        print(f"seen users AUC {seen_auc:.4f} (random effects active)  vs  "
              f"unseen users AUC {unseen_auc:.4f} (fixed effect only)")


if __name__ == "__main__":
    main()
