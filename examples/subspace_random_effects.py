"""Subspace random effects: per-entity models that never densify.

The regime: a random effect per user over a large sparse feature
vocabulary (here d=200k, 20k users). Neither the (n, d) data matrix nor
the (E, d) model table is ever materialized — examples stage into padded
buckets at each entity's active dimension (LinearSubspaceProjector
parity), and the trained model keeps (E, A) active-column coefficients
(`SubspaceRandomEffectModel`, the reference's
RandomEffectModelInProjectedSpace). Measured at full scale on one TPU
chip: 10M rows / 1M entities / d=1M trains in ~2-4 min steady-state
(docs/PARITY.md).

Run on CPU (virtual mesh) or a TPU:

    python examples/subspace_random_effects.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)
import os
import tempfile
import time

import numpy as np

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       RandomEffectDataConfiguration)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.game.models import SubspaceRandomEffectModel
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

enable_compilation_cache()


def make_data(rng, n, num_entities, d, nnz=6, pool=12, pools=None):
    """Per-user examples over user-specific feature pools with planted
    per-user coefficients (so the random effect is what carries signal).
    Pass ``pools`` to draw fresh examples over the SAME per-user feature
    spaces (scoring-time data)."""
    ids = rng.integers(0, num_entities, n).astype(np.int32)
    if pools is None:
        pools = rng.integers(0, d, (num_entities, pool)).astype(np.int32)
    slot = rng.integers(0, pool, (n, nnz))
    idx = np.sort(pools[ids[:, None], slot], axis=1)
    dup = np.zeros_like(idx, bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    idx[dup] = d
    vals[dup] = 0.0
    beta = rng.normal(0, 1.2, size=(num_entities, pool)).astype(np.float32)
    margin = (np.where(dup, 0.0, vals) * beta[ids[:, None], slot]).sum(1)
    y = (rng.random(n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    return GameDataset(
        response=y, offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
        feature_shards={"re_user": SparseShard(idx, vals, d)},
        entity_ids={"userId": ids},
        num_entities={"userId": num_entities},
        intercept_index={}), pools


def main():
    rng = np.random.default_rng(0)
    n, E, d = 200_000, 20_000, 200_000
    print(f"data: n={n:,} rows, {E:,} users, d={d:,} sparse features")
    ds, pools = make_data(rng, n, E, d)

    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=25, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "per-user": CoordinateConfiguration(
                data=RandomEffectDataConfiguration(
                    "userId", "re_user", active_data_lower_bound=2,
                    projector="INDEX_MAP"),  # subspace_model=None → auto
                optimization=opt),
        },
        update_sequence=["per-user"],
        mesh=make_mesh(), validation_evaluators=["AUC"])

    t0 = time.perf_counter()
    result = est.fit(ds, validation_data=ds)[0]
    m = result.model.models["per-user"]
    print(f"fit in {time.perf_counter() - t0:.1f}s; "
          f"AUC {result.evaluation.primary_value:.3f}")
    # E·d = 4·10⁹ > the ~1 GiB auto threshold → subspace representation.
    assert isinstance(m, SubspaceRandomEffectModel), type(m)
    print(f"model: SubspaceRandomEffectModel cols/means "
          f"{tuple(m.cols.shape)} (dense table would be {E:,}×{d:,} = "
          f"{E * d * 4 / 2**30:.0f} GiB)")

    # Round trip through the npz model directory and score fresh data.
    from photon_ml_tpu.models.io import load_game_model, save_game_model
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "model")
        save_game_model(result.model, path)
        loaded = load_game_model(path)
    fresh, _ = make_data(np.random.default_rng(1), 20_000, E, d, pools=pools)
    s1 = np.asarray(result.model.score(fresh))
    s2 = np.asarray(loaded.score(fresh))
    np.testing.assert_allclose(s2, s1, rtol=1e-5, atol=1e-6)
    print(f"save/load round trip scores identically on fresh data "
          f"(|scores|₂ {np.linalg.norm(s1):.2f})")


if __name__ == "__main__":
    main()
