"""Hyperparameter tuning: GP + expected-improvement Bayesian search over
per-coordinate regularization weights, seeded by a grid sweep (reference:
GameTrainingDriver's hyperParameterTuning mode).

Run: python examples/hyperparameter_tuning.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)
import numpy as np

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FixedEffectDataConfiguration)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.data import synthetic
from photon_ml_tpu.data.game_data import from_synthetic
from photon_ml_tpu.hyperparameter.evaluation import GameEvaluationFunction
from photon_ml_tpu.hyperparameter.search import GaussianProcessSearch
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.ranges import DoubleRange


def main():
    rng = np.random.default_rng(0)
    n = 4000
    ds = from_synthetic(synthetic.game_data(rng, n=n, d_global=12,
                                            re_specs={}))
    idx = rng.permutation(n)
    train, val = ds.subset(idx[:int(0.8 * n)]), ds.subset(idx[int(0.8 * n):])

    estimator = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={"fixed": CoordinateConfiguration(
            data=FixedEffectDataConfiguration("global"),
            optimization=GLMOptimizationConfiguration(
                optimizer=OptimizerConfig(max_iterations=60),
                regularization=RegularizationContext(
                    RegularizationType.L2, 1.0)),
            reg_weight_grid=(0.01, 100.0))},
        update_sequence=["fixed"],
        mesh=make_mesh(),
        validation_evaluators=["AUC"])

    # Grid sweep first; its results seed the Bayesian search as priors.
    grid_results = estimator.fit(train, validation_data=val)
    evalfn = GameEvaluationFunction(estimator, train, val, ["fixed"],
                                    reg_weight_range=DoubleRange(1e-3, 1e3))
    searcher = GaussianProcessSearch(evalfn.dimensions(), evalfn)
    search = searcher.find_with_priors(
        6, evalfn.observations_from_results(grid_results))

    print("observations (reg weight -> negated AUC):")
    for o in search.observations:
        print(f"  {o.point[0]:10.4g} -> {o.value:.4f}")
    print(f"best: reg={search.best_point[0]:.4g} "
          f"AUC={-search.best_value:.3f}")


if __name__ == "__main__":
    main()
