"""Factored (matrix-factorization) random effects walkthrough.

When entities are many and their per-entity signal is low-rank — the
classic recommender regime — constraining every per-entity model to a
shared rank-r subspace (``w_e = A z_e``) cuts parameters from E·d to
E·r + d·r and regularizes heavily-sparse entities through the shared
projection. This script compares three per-user coordinates on data with
planted rank-2 structure:

- full-rank random effects (one d-dim model per user),
- factored random effects at rank 2 (alternating latent/matrix steps),
- a frozen Gaussian random projection at dimension 4 (projector=RANDOM).

Run: python examples/factored_random_effects.py
"""

import _bootstrap  # noqa: F401  (repo-root sys.path)
import numpy as np

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FactoredRandomEffectDataConfiguration,
                                       FixedEffectDataConfiguration,
                                       RandomEffectDataConfiguration)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.data import synthetic
from photon_ml_tpu.data.game_data import from_synthetic
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType


def make_low_rank_data(rng, n=20_000, n_users=200, d=16, rank=2):
    """Train + held-out datasets sharing one planted rank-2 W = Z Aᵀ.

    Held-out evaluation is the point of the comparison: on train AUC the
    full-rank coordinate can only win (it nests the factored model class);
    generalization is where the shared-subspace regularization shows."""
    syn = synthetic.game_data(rng, n=n, d_global=6,
                              re_specs={"userId": (n_users, d)})
    ds = from_synthetic(syn)
    A = rng.normal(size=(d, rank)).astype(np.float32)
    Z = rng.normal(size=(n_users, rank)).astype(np.float32)
    W = Z @ A.T
    X = ds.feature_shards["re_userId"]
    ids = ds.entity_ids["userId"]
    margin = np.einsum("nd,nd->n", X, W[ids])
    p = 1.0 / (1.0 + np.exp(-margin))
    ds.response = (rng.uniform(size=n) < p).astype(np.float32)
    ds.offsets = np.zeros(n, np.float32)
    split = int(0.8 * n)
    perm = rng.permutation(n)
    return ds.subset(perm[:split]), ds.subset(perm[split:])


def main():
    rng = np.random.default_rng(0)
    train, heldout = make_low_rank_data(rng)
    mesh = make_mesh()
    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=60, tolerance=1e-8),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))

    variants = {
        "full-rank": RandomEffectDataConfiguration("userId", "re_userId"),
        "factored-r2": FactoredRandomEffectDataConfiguration(
            "userId", "re_userId", rank=2, alternations=3),
        "random-proj-4": RandomEffectDataConfiguration(
            "userId", "re_userId", projector="RANDOM",
            projected_dimension=4),
    }
    for name, data_cfg in variants.items():
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinates={
                "fixed": CoordinateConfiguration(
                    data=FixedEffectDataConfiguration("global"),
                    optimization=opt),
                "per-user": CoordinateConfiguration(data=data_cfg,
                                                    optimization=opt),
            },
            update_sequence=["fixed", "per-user"],
            descent_iterations=2,
            mesh=mesh,
            validation_evaluators=["AUC"],
        )
        result = est.fit(train, validation_data=heldout)[0]
        auc = result.evaluation.primary_value
        m = result.model.models["per-user"]
        n_params = (m.factors.size + m.projection.size
                    if hasattr(m, "factors") else m.means.size)
        print(f"{name:>14}: held-out AUC {auc:.4f}  "
              f"({n_params:,} RE parameters)")


if __name__ == "__main__":
    main()
